"""Chaos scenario runners: drive a live engine through a seeded
`ChaosSchedule`, injecting faults at every seam, checking invariants
every tick.

Six runners, covering three planes:

  * `FusedChaosRunner` — the fused single-dispatch runtime
    (runtime/fused.py FusedClusterNode).  Fully deterministic: one
    thread drives `tick()` manually, fault masks are host-generated
    from the schedule's seed, crashes are simulated in-process, and
    the run's result digest is reproducible bit-for-bit from the seed
    (`make chaos` proves it by running a seed twice).  Also carries
    the asym-partition, per-peer clock-skew, ENOSPC, fsync-stall, and
    compaction-interleaving families.
  * `NodeClusterChaosRunner` — the threaded/distributed runtime
    (runtime/node.py RaftNode) as a LOCKSTEP cluster over the loopback
    transport: per-node crash/restart, leader-targeted kills, FaultPlan
    partitions (bidirectional and one-directional), per-node timer
    skew, and seeded wire-frame corruption, with per-node durability
    and cross-node log matching checked from the commit streams.
  * `SnapshotChaosRunner` — the node runner plus per-node KV state
    machines, aggressive compaction, and InstallSnapshot transfers,
    ending in the post-snapshot survivor CONVERGENCE invariant.
  * `TcpClusterChaosRunner` — the same node cluster over the REAL TCP
    transport (transport/tcp.py) with its injectable send-side fault
    seam: drops, one-directional blocks, frame corruption (CRC-dropped
    and counted at the receivers), delayed frames.
  * `MembershipChaosRunner` — dynamic-membership churn on the lockstep
    plane (raftsql_tpu/membership/): permanent SIGKILL + fresh-machine
    replacement via add-learner -> promote (joint consensus) ->
    remove-dead, under drops/partitions/crashes, with the
    RemovedQuorumSafety invariant and a final-config convergence +
    progress check.
  * `TcpRebindChaosRunner` — TCP-plane crash/restart with PORT
    REBINDING: listeners close, the same ports are rebound on restart,
    peers must reconnect and the restarted node must catch up.

Crash simulation ("hard crash"): every open durable fd of the dying
node is redirected to /dev/null before the object is abandoned — a
buffered-but-unflushed byte can then never be resurrected by a later
GC flush into the file the restarted node is appending to.  That IS a
process kill's semantics (userspace buffers lost, flushed page-cache
bytes kept).  A POWER LOSS additionally truncates every file to its
last really-fsynced size, optionally tearing one peer's last record
mid-write (storage/fsio.py records both) — which is exactly the state
WAL._repair_tail and the epoch-repair path exist to recover.
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import socket
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from raftsql_tpu.chaos.invariants import (CommitMonotonic,
                                          DurabilityLedger, ElectionSafety,
                                          InvariantViolation,
                                          RegisterLinearizability,
                                          RemovedQuorumSafety,
                                          check_convergence,
                                          check_log_matching)
from raftsql_tpu.chaos.schedule import (LEADER_TARGET, ChaosSchedule,
                                        MembershipChaosPlan, NodeChaosPlan,
                                        TcpChaosPlan, TcpRebindPlan)
from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.runtime.db import _expand_commit_item, iter_plain_batches
from raftsql_tpu.runtime.fused import FusedClusterNode
from raftsql_tpu.runtime.node import CLOSED, RaftNode
from raftsql_tpu.storage import fsio
from raftsql_tpu.transport.faults import (asym_partition, drop_messages,
                                          hold_messages, partition_peer,
                                          release_messages)
from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport
from raftsql_tpu.transport.tcp import SendFaults, TcpTransport

DEAD_ROLE = -1          # role code for a crashed node's safety-matrix row

# Post-heal settle budget (NodeClusterChaosRunner.run): extra fault-free
# ticks allowed for in-flight apply pipelines to drain before the
# convergence check.  Healthy runs need 1-2 (one batched publish of
# lag); the cap keeps a genuinely diverged peer a loud failure.
SETTLE_TICKS_MAX = 40


def _redirect_to_devnull(files) -> None:
    """dup2 /dev/null over every open fd so abandoned buffered writers
    can never flush real bytes later."""
    devnull = os.open(os.devnull, os.O_WRONLY)
    try:
        for f in files:
            if f is not None and not f.closed:
                os.dup2(devnull, f.fileno())
    finally:
        os.close(devnull)


def hard_crash_fused(node: FusedClusterNode) -> None:
    """Simulate a process kill of the whole fused/mesh cluster process.

    Requires the Python WAL backend (an installed fsio injector forces
    it): the native backend buffers inside C++ where this simulation
    cannot reach.  A mesh node's per-shard WALs (runtime/mesh.py
    ShardedWAL) expand to their per-shard file handles."""
    files = []
    for w in node.wals:
        for s in getattr(w, "shards", (w,)):
            files.append(getattr(s, "_f", None))
    _redirect_to_devnull(files + [node._epoch_f])
    # Unblock the publish workers so the abandoned daemon threads exit
    # instead of leaking threads per simulated crash.
    for q in node._pub_qs:
        try:
            q.put_nowait(None)
        except queue.Full:               # pragma: no cover - bounded lag
            pass


def hard_crash_node(node: RaftNode) -> None:
    """Simulate a process kill of one RaftNode: WAL fd neutered, then
    detached from the loopback hub (its 'NIC' goes dark)."""
    _redirect_to_devnull([getattr(node.wal, "_f", None)])
    node.transport.stop()


def _power_loss(inj: fsio.StorageFaultInjector, data_dir: str,
                tear_peer: int = -1) -> Tuple[int, int]:
    """Apply power-loss semantics to every tracked file under data_dir:
    drop everything after the last real fsync, tearing (keeping a
    partial prefix of) the tear peer's last unsynced record instead of
    dropping it whole.  Returns (files_truncated, records_torn)."""
    torn = dropped = 0
    tear_paths = set()
    if tear_peer >= 0:
        tag = os.sep + f"p{tear_peer + 1}" + os.sep
        for path in inj.tracked_paths():
            if path.startswith(data_dir) and tag in path \
                    and inj.tear_last_write(path):
                torn += 1
                tear_paths.add(path)
    for path in inj.tracked_paths():
        if path.startswith(data_dir) and path not in tear_paths \
                and inj.drop_unsynced(path):
            dropped += 1
    return dropped, torn


def _drain_fused_q(q: "queue.Queue") -> List[Tuple[int, int, List[bytes]]]:
    """Drain a fused commit queue non-blocking into plain
    (group, base_idx, [payload, ...]) batches (sentinels skipped)."""
    batches: List[Tuple[int, int, List[bytes]]] = []
    while True:
        try:
            item = q.get_nowait()
        except queue.Empty:
            return batches
        if item is None:
            continue
        if item is CLOSED:
            return batches
        batches.extend(iter_plain_batches(item))


class FusedChaosRunner:
    """Drive a FusedClusterNode through a ChaosSchedule.

    Workload: seeded unique-value PUTs (`SET k<K> v<seq>`) routed by
    key to a group, plus linearizable GETs registered through
    `read_index` and resolved against peer 0's applied state.  Every
    tick: release due delayed messages, apply the tick's fault masks,
    issue workload, dispatch, flush+drain publishes, resolve reads,
    observe invariants.  Crashes (scheduled, or triggered by an
    injected fsync failure) restart the cluster from its WALs and
    verify the durability ledger against the replay.
    """

    KEYS = 8
    LOG_MATCH_EVERY = 16
    # Which peers' commit queues the engine materializes (peer 0 is the
    # client apply plane).  ReadNemesisRunner sets None (= all): its
    # per-peer read serving state needs every peer's stream.
    PUBLISH_PEERS: Optional[set] = {0}

    def __init__(self, schedule: ChaosSchedule, data_dir: str,
                 cfg: Optional[RaftConfig] = None, steps: int = 1):
        self.sched = schedule
        self.data_dir = data_dir
        # Compacting schedules get a small device window so the clamped
        # compaction floor (keep >= log_window) actually advances within
        # a fast run's entry counts.
        self.cfg = cfg or RaftConfig(
            num_groups=4, num_peers=schedule_peers(schedule),
            log_window=16 if schedule.compact_every else 64,
            max_entries_per_msg=4, election_ticks=10,
            heartbeat_ticks=1, tick_interval_s=0.0)
        self.steps = steps
        self.node: Optional[FusedClusterNode] = None
        self.ledger = DurabilityLedger()
        self.lin = RegisterLinearizability()
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(self.cfg.num_peers,
                                         self.cfg.num_groups)
        self._kv: Dict[str, str] = {}
        self._applied = np.zeros(self.cfg.num_groups, np.int64)
        self._held: List[Tuple[int, object]] = []
        self._pending_reads: List[Tuple[str, int, int, tuple]] = []
        self._part_peer: Dict[int, int] = {}
        self._asym_src: Dict[int, int] = {}
        self._wseq = 0
        self.final_metrics = None       # NodeMetrics after run()
        self.report: Dict[str, int] = {
            "crashes": 0, "restarts": 0, "partitions": 0,
            "asym_partitions": 0, "skew_ticks": 0,
            "fsync_faults": 0, "torn_write_faults": 0, "torn_writes": 0,
            "enospc_hits": 0, "fsync_stalls": 0, "compactions": 0,
            "unsynced_files_dropped": 0, "dropped_slots": 0,
            "delayed_slots": 0, "log_match_checks": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def _make_node(self) -> FusedClusterNode:
        """Construct the engine under test; MeshChaosRunner overrides
        this with the mesh runtime (same host plane, sharded device
        step + sharded WAL dirs)."""
        return FusedClusterNode(self.cfg, self.data_dir,
                                seed=self.sched.seed)

    def _boot(self, first: bool) -> FusedClusterNode:
        node = self._make_node()
        if self.steps > 1:
            node._steps = self.steps
        node.publish_peers = self.PUBLISH_PEERS
        # Flight recorder feed (raftsql_tpu/obs/): device event ring +
        # host spans, dumped next to the seed on invariant failure.
        # Tracing never touches consensus state, so the run's schedule
        # and result digests are unchanged.
        node.enable_tracing()
        replayed: Dict[Tuple[int, int], bytes] = {}
        order: List[Tuple[int, int, bytes]] = []
        for p in range(self.cfg.num_peers):
            batches = _drain_fused_q(node.commit_q(p))
            if p == 0:                   # peer 0's stream is the client
                for (g, base, datas) in batches:
                    for off, d in enumerate(datas):
                        if d:
                            replayed[(g, base + 1 + off)] = d
                            order.append((g, base + 1 + off, d))
            self._boot_peer_drained(p, batches)
        # Compaction floors: the replay legitimately starts above them
        # (compact() only ever drops published entries — the publish
        # cursor gates the floor).
        floors = np.array([node.plogs[0].start(g)
                           for g in range(self.cfg.num_groups)], np.int64)
        if not first:
            self.ledger.verify_replay(
                replayed, context=f"restart {self.report['restarts']}",
                floors=floors)
            self.report["restarts"] += 1
        # Rebuild the client-visible KV state: the compacted prefix from
        # the durability ledger (the runner's stand-in for the state-
        # machine snapshot real compaction is gated on), then the
        # replayed stream above it (per-group index order; groups are
        # independent key spaces).
        self._kv.clear()
        for g, i, d in sorted(
                (g, i, d) for (g, i), d in self.ledger._committed.items()
                if i <= floors[g]):
            self._apply(g, i, d)
        for g, i, d in sorted(order):
            self._apply(g, i, d)
        self._applied = node._applied[0].copy()
        node.metrics.faults_crashes = self.report["crashes"]
        return node

    def _boot_peer_drained(self, p: int, batches) -> None:
        """Subclass seam: peer p's replay stream was just drained at
        (re)boot — ReadNemesisRunner rebuilds its per-peer read state
        here."""

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1) -> None:
        hard_crash_fused(self.node)
        self.report["crashes"] += 1
        if power_loss:
            inj = fsio.injector()
            dropped, torn = _power_loss(inj, self.data_dir, tear_peer)
            self.report["unsynced_files_dropped"] += dropped
            self.report["torn_writes"] += torn
        # In-flight state dies with the process: delayed messages and
        # registered-but-unresolved reads (their clients aborted).
        self._held.clear()
        self._pending_reads.clear()
        self.node = self._boot(first=False)

    # -- workload ------------------------------------------------------

    def _apply(self, g: int, idx: int, payload: bytes) -> None:
        self.ledger.record(g, idx, payload)
        parts = payload.decode("utf-8").split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            self._kv[parts[1]] = parts[2]
            self.lin.end_write(parts[2])
        self._applied[g] = max(self._applied[g], idx)

    def _issue(self, rng: np.random.Generator) -> None:
        if rng.random() < self.sched.prop_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % self.cfg.num_groups
            value = f"v{self._wseq}"
            self._wseq += 1
            self.lin.begin_write(f"k{k}", value)
            self.node.propose_many(g, [f"SET k{k} {value}".encode()])
        if rng.random() < self.sched.read_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % self.cfg.num_groups
            got = self.node.read_index(g)
            if got:                       # leaderless: client retries later
                target, _ = got
                self._pending_reads.append(
                    (f"k{k}", g, target, self.lin.begin_read(f"k{k}")))

    def _drain_tick(self) -> None:
        """Consume the client (peer 0) commit stream after a tick —
        ReadNemesisRunner overrides to drain every peer into its
        per-peer read state too."""
        for (g, base, datas) in _drain_fused_q(self.node.commit_q(0)):
            for off, d in enumerate(datas):
                if d:
                    self._apply(g, base + 1 + off, d)
        self._applied = np.maximum(self._applied,
                                   self.node._applied[0])

    def _resolve_reads(self) -> None:
        still = []
        for (key, g, target, handle) in self._pending_reads:
            if self._applied[g] >= target:
                self.lin.end_read(handle, self._kv.get(key, ""))
            else:
                still.append((key, g, target, handle))
        self._pending_reads = still

    # -- fault application ---------------------------------------------

    def _apply_faults(self, t: int, rng: np.random.Generator) -> None:
        node = self.node
        due = [h for (rt, h) in self._held if rt <= t]
        self._held = [(rt, h) for (rt, h) in self._held if rt > t]
        for h in due:                    # released mail is subject to
            node.inboxes = release_messages(node.inboxes, h)  # this
        shape = node.inboxes.v_type.shape          # tick's masks below
        for w in self.sched.delays:
            if w.start <= t < w.end:
                mask = rng.random(shape) < w.p
                if mask.any():
                    delivered, held = hold_messages(node.inboxes,
                                                    jnp.asarray(mask))
                    node.inboxes = delivered
                    self._held.append((t + w.latency, held))
                    self.report["delayed_slots"] += int(mask.sum())
        for w in self.sched.drops:
            if w.start <= t < w.end:
                mask = rng.random(shape) < w.p
                if mask.any():
                    node.inboxes = drop_messages(node.inboxes,
                                                 jnp.asarray(mask))
                    self.report["dropped_slots"] += int(mask.sum())
        for wi, w in enumerate(self.sched.partitions):
            if w.start <= t < w.end:
                peer = self._part_peer.get(wi)
                if peer is None:
                    peer = w.peer if w.peer >= 0 \
                        else max(self.node.leader_of(0), 0)
                    self._part_peer[wi] = peer
                    self.report["partitions"] += 1
                node.inboxes = partition_peer(node.inboxes, peer)
        for wi, w in enumerate(self.sched.asym_partitions):
            if w.start <= t < w.end:
                src = self._asym_src.get(wi)
                if src is None:
                    # LEADER_TARGET: the window's one-directional cut is
                    # anchored on whoever leads group 0 at its opening
                    # tick — "dst goes deaf to its leader".
                    src = w.src if w.src >= 0 \
                        else max(self.node.leader_of(0), 0)
                    self._asym_src[wi] = src
                    self.report["asym_partitions"] += 1
                node.inboxes = asym_partition(node.inboxes, src, w.dst)

    def _skew_for(self, t: int) -> Optional[np.ndarray]:
        """Per-peer timer_inc for tick t, None = lockstep.  Later
        windows override earlier ones on overlap (schedules keep them
        disjoint in practice)."""
        ti = None
        for w in self.sched.skews:
            if w.start <= t < w.end:
                ti = np.asarray(w.incs, np.int32)
        return ti

    # -- invariants ----------------------------------------------------

    def _observe(self, t: int) -> None:
        node = self.node
        roles = node.roles()
        terms = np.asarray(node.states.term)
        self.safety.observe(t, roles, terms)
        commits = node._hard[:, :, 2]
        self.monotonic.observe(t, commits)
        if t % self.LOG_MATCH_EVERY == 0:
            check_log_matching(t, commits, node.plogs)
            self.report["log_match_checks"] += 1

    # -- the run -------------------------------------------------------

    def run(self) -> dict:
        inj = fsio.StorageFaultInjector()
        for f in self.sched.fsync_faults:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         fail_at=(f.op,))
        for f in self.sched.torn_writes:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         crash_write_at=(f.op,), tag=f.peer)
        for f in self.sched.enospc_faults:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         enospc_write_at=(f.op,))
        for f in self.sched.fsync_stalls:
            inj.add_rule(os.sep + f"p{f.peer + 1}" + os.sep,
                         stall_at=tuple(range(f.op, f.op + f.count)),
                         stall_s=f.stall_s)
        crash_at = {ev.tick: ev for ev in self.sched.crashes}
        rng = np.random.default_rng(self.sched.seed + 1)
        with fsio.installed(inj):
            self.node = self._boot(first=True)
            try:
                for t in range(self.sched.ticks):
                    ev = crash_at.get(t)
                    if ev is not None:
                        self._crash_restart(t, ev.power_loss,
                                            ev.tear_peer)
                    self._apply_faults(t, rng)
                    self._issue(rng)
                    ti = self._skew_for(t)
                    if ti is not None:
                        self.report["skew_ticks"] += int(
                            np.abs(ti.astype(np.int64) - 1).sum())
                    self.node.timer_inc = ti
                    try:
                        self.node.tick()
                        # With double-buffered dispatch (hostplane
                        # overlap) the tick's durable phase is stashed;
                        # this drain retires it, so the injected
                        # storage faults fire HERE — same ops, same
                        # order, same crash posture as the serialized
                        # pipeline (digests must not move).
                        self.node.publish_flush()
                    except fsio.EnospcError:
                        # Disk full on a WAL append: the tick's durable
                        # barrier cannot complete, so this is fatal
                        # (same posture as a failed fsync) — crash +
                        # restart.  The consumed trigger models the
                        # operator freeing space; the retried record
                        # lands on a clean tail.
                        self.report["enospc_hits"] += 1
                        self._crash_restart(t, power_loss=False)
                        continue
                    except fsio.FsyncFaultError:
                        # etcd posture: a failed WAL fsync is fatal —
                        # crash the process rather than ack unsynced
                        # data; the restart replays the durable prefix.
                        self.report["fsync_faults"] += 1
                        self._crash_restart(t, power_loss=False)
                        continue
                    except fsio.CrashPointError as e:
                        # Power loss mid-record: the machine dies with
                        # the record partially written and the tick's
                        # barrier never reached — tear that record,
                        # drop every unsynced tail, restart.
                        self.report["torn_write_faults"] += 1
                        self._crash_restart(t, power_loss=True,
                                            tear_peer=int(e.tag))
                        continue
                    self._drain_tick()
                    self._resolve_reads()
                    self._observe(t)
                    if self.sched.compact_every and t \
                            and t % self.sched.compact_every == 0 \
                            and self.node.compact(
                                keep=self.sched.compact_keep):
                        self.report["compactions"] += 1
                # Final deep checks + a restart pass so the run always
                # ends with a full durability audit.
                check_log_matching(self.sched.ticks,
                                   self.node._hard[:, :, 2],
                                   self.node.plogs)
                self.report["log_match_checks"] += 1
                self.node.timer_inc = None
                self._crash_restart(self.sched.ticks)
                self.report["fsync_stalls"] = inj.fsync_stalls
                m = self.node.metrics
                m.faults_dropped_msgs = self.report["dropped_slots"]
                m.faults_delayed_msgs = self.report["delayed_slots"]
                m.faults_partitions = self.report["partitions"]
                m.faults_fsync = self.report["fsync_faults"]
                m.faults_enospc = self.report["enospc_hits"]
                m.faults_fsync_stalls = self.report["fsync_stalls"]
                m.faults_skew_ticks = self.report["skew_ticks"]
                # Survives node teardown so tests can assert the
                # exported counters (the /metrics surface).
                self.final_metrics = m
            except InvariantViolation as e:
                # Flight recorder: every invariant failure becomes a
                # post-mortem artifact — the last N ticks of device
                # events plus the host spans, next to the failing seed.
                self._flight_dump(e)
                raise
            finally:
                node, self.node = self.node, None
                if node is not None:
                    node.stop()
        return self._report()

    def _flight_dump(self, err: Exception) -> None:
        from raftsql_tpu.obs.flight import FlightRecorder
        node = self.node
        if node is None:
            return
        FlightRecorder().dump(
            f"fused-seed{self.sched.seed}", repr(err),
            tracer=node.tracer, ring=node.ring, node=node,
            meta={"seed": self.sched.seed,
                  "schedule_digest": self.sched.digest(),
                  "report": dict(self.report)})

    def _report(self) -> dict:
        committed = sorted(
            (g, i, d.decode("utf-8"))
            for (g, i), d in self.ledger._committed.items())
        blob = json.dumps(
            {"committed": committed, "report": self.report,
             "writes": self._wseq, "reads": self.lin.reads_checked},
            sort_keys=True, separators=(",", ":")).encode()
        return {
            "seed": self.sched.seed,
            "ticks": self.sched.ticks,
            "schedule_digest": self.sched.digest(),
            "result_digest": hashlib.sha256(blob).hexdigest()[:16],
            "committed_entries": len(self.ledger),
            "writes_issued": self._wseq,
            "reads_checked": self.lin.reads_checked,
            "safety_observations": self.safety.observations,
            **self.report,
        }


class MeshChaosRunner(FusedChaosRunner):
    """FusedChaosRunner over the MESH runtime (runtime/mesh.py): the
    same seeded schedules, workload, invariants and durability audit,
    with the device step shard_map'd over a groups-sharded mesh and the
    host plane's WALs split per group shard.  Exercises the mesh-skew
    frontier the old `MeshLockstepOnlyError` used to fence off: chaos
    SkewWindow schedules drive the sharded step's per-peer timer
    vector, and crash/restart replays from the per-shard WAL dirs.

    Deterministic like the fused runner: the mesh is pure SPMD math
    (sharding is an execution detail, never a semantics change — see
    tests/test_parallel.py), so schedule + result digests must
    reproduce across runs and MATCH the fused runner's for the same
    schedule."""

    def __init__(self, schedule: ChaosSchedule, data_dir: str,
                 cfg: Optional[RaftConfig] = None, steps: int = 1):
        super().__init__(schedule, data_dir, cfg=cfg, steps=steps)
        from raftsql_tpu.runtime.mesh import MeshConfig
        self.mesh_config = MeshConfig.for_groups(self.cfg)
        if self.mesh_config.group_shards < 2:
            raise RuntimeError(
                f"mesh chaos needs >= 2 group shards, have "
                f"{len(jax.devices())} devices for "
                f"{self.cfg.num_groups} groups — force a multi-device "
                "CPU platform with "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8")
        self.mesh = self.mesh_config.build()

    def _make_node(self):
        from raftsql_tpu.runtime.mesh import MeshClusterNode
        return MeshClusterNode(self.cfg, self.data_dir, self.mesh,
                               seed=self.sched.seed)


class ReadNemesisRunner(FusedChaosRunner):
    """The read-linearizability nemesis (fused plane): every read mode
    of the lease read plane — lease, ReadIndex, session, follower —
    races the write stream while clock skew, leader-targeted
    partitions, asymmetric cuts, and crashes land.

    Serving model (what a real multi-process deployment would do,
    simulated honestly): every peer's commit stream is drained into a
    PER-PEER KV (`publish_peers = None`), and a read served "at peer
    p" resolves against peer p's applied state — NOT the global truth.
    A partitioned stale leader therefore really can serve an old
    value, and only the lease bound stands between that and a
    linearizability violation:

      * LEASE reads are issued at EVERY peer whose device lease
        (core/step.py Phase 8b) currently covers now + max_clock_skew
        — including a deposed leader that does not know it yet.  Under
        a correctly sized bound (lease_ticks + max_clock_skew <=
        election_ticks / max_skew_rate) the real-time register
        invariant must never fire; the falsification plan
        (schedule.py falsification_plan) oversizes the lease under 4x
        skew and the invariant MUST fire — proving the harness detects
        a broken bound, not just chaos.
      * READINDEX reads ride the base runner's read_index workload.
      * SESSION reads present the watermark of the client's last
        completed write and resolve at a RANDOM peer once its apply
        passes the watermark — checked by SessionConsistency
        (read-your-writes), which unlike the register rule permits
        legally-stale-but-watermark-fresh answers.
      * FOLLOWER reads use the serving peer's own commit index as the
        watermark (the replicated read-index watermark).

    Fully deterministic: same seeded draws as the base runner, digest
    compared across runs by `make chaos-reads`.
    """

    PUBLISH_PEERS: Optional[set] = None       # drain every peer

    def __init__(self, plan, data_dir: str):
        from raftsql_tpu.chaos.invariants import SessionConsistency
        from raftsql_tpu.chaos.schedule import ChaosSchedule as _CS
        sched = _CS(seed=plan.seed, ticks=plan.ticks,
                    partitions=plan.partitions,
                    asym_partitions=plan.asym_partitions,
                    skews=plan.skews, crashes=plan.crashes,
                    prop_rate=plan.prop_rate,
                    read_rate=plan.read_index_rate)
        cfg = RaftConfig(num_groups=plan.groups, num_peers=plan.peers,
                         log_window=64, max_entries_per_msg=4,
                         election_ticks=plan.election_ticks,
                         heartbeat_ticks=1, tick_interval_s=0.0,
                         lease_ticks=plan.lease_ticks,
                         max_clock_skew=plan.max_clock_skew,
                         # Quorum-geometry plans (QuorumNemesisPlan)
                         # carry these; ReadNemesisPlan does not, and
                         # the defaults leave the config on the static
                         # full-voter fast path.
                         write_quorum=getattr(plan, "write_quorum",
                                              None),
                         election_quorum=getattr(plan,
                                                 "election_quorum",
                                                 None),
                         witnesses=getattr(plan, "witnesses",
                                           None) or None,
                         unsafe_quorum_geometry=getattr(
                             plan, "unsafe_geometry", False),
                         unsafe_witness_lease=getattr(
                             plan, "broken_witness_lease", False))
        super().__init__(sched, data_dir, cfg=cfg)
        self.plan = plan
        P, G = plan.peers, plan.groups
        self._pkv: List[Dict[str, str]] = [dict() for _ in range(P)]
        self._papplied = np.zeros((P, G), np.int64)
        self.session = SessionConsistency()
        # (peer, key, group, target_commit, register handle)
        self._pending_lease: List[tuple] = []
        # (peer, key, group, watermark, mode)
        self._pending_session: List[tuple] = []
        # key -> (group, watermark) of its last COMPLETED write — the
        # session a client would carry (X-Raft-Session).
        self._last_wm: Dict[str, Tuple[int, int]] = {}
        self.report.update({
            "lease_reads": 0, "session_reads": 0, "follower_reads": 0,
            "lease_peers_leased": 0,
        })

    # -- per-peer apply plane -------------------------------------------

    def _note_peer_apply(self, p: int, g: int, idx: int,
                         payload: bytes) -> None:
        parts = payload.decode("utf-8").split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            self._pkv[p][parts[1]] = parts[2]
            # Committed-history feed for the session checker (first
            # peer to surface an index wins; log matching keeps every
            # later copy identical).
            self.session.note_commit(g, idx, parts[1], parts[2])

    def _boot_peer_drained(self, p: int, batches) -> None:
        self._pkv[p] = {}
        for (g, base, datas) in batches:
            for off, d in enumerate(datas):
                if d:
                    self._note_peer_apply(p, g, base + 1 + off, d)

    def _boot(self, first: bool):
        # In-flight per-peer reads die with the process, like the base
        # runner's pending ReadIndex reads.
        self._pending_lease.clear()
        self._pending_session.clear()
        node = super()._boot(first)
        self._papplied = node._applied.copy()
        return node

    def _drain_tick(self) -> None:
        node = self.node
        for p in range(self.cfg.num_peers):
            for (g, base, datas) in _drain_fused_q(node.commit_q(p)):
                for off, d in enumerate(datas):
                    if not d:
                        continue
                    idx = base + 1 + off
                    if p == 0:
                        self._apply(g, idx, d)
                    self._note_peer_apply(p, g, idx, d)
        self._applied = np.maximum(self._applied, node._applied[0])
        self._papplied = np.maximum(self._papplied, node._applied)

    def _apply(self, g: int, idx: int, payload: bytes) -> None:
        super()._apply(g, idx, payload)
        parts = payload.decode("utf-8").split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            # The write just COMPLETED (client apply = ack): its
            # watermark is what a session client would carry forward.
            self._last_wm[parts[1]] = (g, idx)

    # -- workload --------------------------------------------------------

    def _issue(self, rng: np.random.Generator) -> None:
        super()._issue(rng)          # writes + ReadIndex reads
        plan = self.plan
        cfg = self.cfg
        P = cfg.num_peers
        node = self.node
        if rng.random() < plan.lease_read_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % cfg.num_groups
            key = f"k{k}"
            lc = node._lease_col
            if lc is not None and cfg.lease_ticks > 0:
                now = node._device_steps
                leased = [p for p in range(P)
                          if int(lc[p, g]) > 0
                          and now + cfg.max_clock_skew < int(lc[p, g])]
                self.report["lease_peers_leased"] += len(leased)
                for p in leased:
                    # The lease read a real deployment would serve AT
                    # PEER p: target = p's commit, answer = p's state.
                    target = int(node._hard[p, g, 2])
                    self.report["lease_reads"] += 1
                    self._pending_lease.append(
                        (p, key, g, target,
                         self.lin.begin_read(key, mode="lease")))
        if rng.random() < plan.session_read_rate and self._last_wm:
            keys = sorted(self._last_wm)
            key = keys[int(rng.integers(0, len(keys)))]
            g, wm = self._last_wm[key]
            p = int(rng.integers(0, P))
            self.report["session_reads"] += 1
            self._pending_session.append((p, key, g, wm, "session"))
        if rng.random() < plan.follower_read_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % cfg.num_groups
            p = int(rng.integers(0, P))
            # Replicated read-index watermark: the serving peer's own
            # commit index at request arrival.
            wm = int(node._hard[p, g, 2])
            self.report["follower_reads"] += 1
            self._pending_session.append((p, f"k{k}", g, wm,
                                          "follower"))

    def _resolve_reads(self) -> None:
        super()._resolve_reads()     # base ReadIndex reads
        still: List[tuple] = []
        for (p, key, g, target, handle) in self._pending_lease:
            if self._papplied[p][g] >= target:
                self.lin.end_read(handle, self._pkv[p].get(key, ""))
            else:
                still.append((p, key, g, target, handle))
        self._pending_lease = still
        still = []
        for (p, key, g, wm, mode) in self._pending_session:
            if self._papplied[p][g] >= wm:
                self.session.check_read(g, key, wm,
                                        self._pkv[p].get(key, ""),
                                        mode=mode)
            else:
                still.append((p, key, g, wm, mode))
        self._pending_session = still

    def _report(self) -> dict:
        r = super()._report()
        r["plan_digest"] = self.plan.digest()
        r["session_reads_checked"] = self.session.reads_checked
        r["reads_by_mode"] = dict(sorted(
            self.lin.reads_by_mode.items()))
        return r


class QuorumChaosRunner(ReadNemesisRunner):
    """The quorum-geometry nemesis (fused plane): flexible
    write/election quorums and witness peers (config.py quorum
    geometry) under the read-nemesis workload and fault families.
    Extends ReadNemesisRunner with three quorum-specific checks:

      * CROSS-PEER commit consistency: every peer's publish stream
        feeds one shared DurabilityLedger keyed (group, index).  Under
        an intersecting geometry (W + E > N) two peers can never
        surface different payloads for one slot — raft's committed-
        entry uniqueness.  The W=1 falsification plan
        (schedule.py falsification_quorum_plan) makes a partitioned
        pinned leader solo-commit acked writes the majority side then
        rewrites; the divergence MUST be caught (this ledger's
        changed-content check, or log matching / commit monotonicity
        if they observe the split first).
      * WITNESS serving audit: a witness's publish stream must stay
        EMPTY (runtime/hostplane.py advances its cursor without
        publishing — it has no apply plane); any payload surfacing
        from a witness is counted in `witness_publishes` and failed by
        the run driver.  The report carries `wal_streams` (every peer
        fsyncs a WAL) vs `apply_streams` (only non-witness peers apply
        — the fsync stream the witness economy saves) and the
        witness's replicated-append count (`witness_appends`, summed
        across crash/restart generations).
      * LEADER PINNING: plans may pin group 0's leadership onto a
        named peer before the fault windows open
        (QuorumNemesisPlan.pin_leader_tick), so directed falsification
        windows can name fixed peer ids.  The stale-lease witness arm
        (schedule.py falsification_witness_plan) relies on it:
        unsafe_witness_lease lets the witness grant a prevote inside
        the deposed leader's live lease, and the resulting stale lease
        read MUST be caught by the register invariant — while the
        honest witness under the SAME schedule must pass.

    Fully deterministic like its bases: digests compared across runs
    by `make chaos-quorum`.
    """

    def __init__(self, plan, data_dir: str):
        from raftsql_tpu.chaos.invariants import DurabilityLedger
        super().__init__(plan, data_dir)
        self._witness_set = frozenset(plan.witnesses)
        # Cross-peer commit view: (group, index) -> payload, fed from
        # EVERY peer's stream (the base ledger only sees peer 0's).
        self._xview = DurabilityLedger()
        # witness_appends survives _crash_restart: bank the dying
        # node's counter before each reboot.
        self._wit_banked = 0
        self._pin_done = False
        self.report.update({
            "wal_streams": plan.peers,
            "apply_streams": plan.peers - len(self._witness_set),
            "witness_publishes": 0,
            "pin_transfers": 0,
        })

    def _note_peer_apply(self, p: int, g: int, idx: int,
                         payload: bytes) -> None:
        if p in self._witness_set:
            self.report["witness_publishes"] += 1
        self._xview.record(g, idx, payload)
        super()._note_peer_apply(p, g, idx, payload)

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1):
        if self.node is not None:
            self._wit_banked += int(self.node.metrics.witness_appends)
        super()._crash_restart(tick, power_loss=power_loss,
                               tear_peer=tear_peer)

    def _apply_faults(self, t: int, rng: np.random.Generator) -> None:
        from raftsql_tpu.runtime.node import TransferRefused
        plan = self.plan
        pt = plan.pin_leader_tick
        if pt >= 0 and pt <= t < pt + 16 and not self._pin_done:
            tgt = plan.pin_leader_peer
            lead = self.node.leader_of(0)
            if lead == tgt:
                self._pin_done = True
            elif lead >= 0:
                try:
                    self.node.transfer_leadership(0, tgt)
                    self.report["pin_transfers"] += 1
                except TransferRefused:
                    pass         # mid-election / in flight: next tick
        super()._apply_faults(t, rng)

    def _report(self) -> dict:
        r = super()._report()
        wit = self._wit_banked
        if self.node is not None:
            wit += int(self.node.metrics.witness_appends)
        r["witness_appends"] = wit
        r["cross_peer_slots"] = len(self._xview)
        return r


class TransferChaosRunner(FusedChaosRunner):
    """The transfer-under-nemesis family (fused plane): graceful
    leadership transfers (runtime/hostplane.py transfer_leadership →
    core/step.py TimeoutNow kernel) race drops, leader-targeted
    partitions, one-directional cuts, clock skew, and crash+restart
    while the acked-PUT workload keeps running — checked by the
    TransferAvailability invariant on top of the standing election-
    safety / durability / linearizability checks:

      * every accepted transfer RESOLVES (completed or aborted) within
        the engine deadline plus a two-election settling margin;
      * a transfer resolving in fault-free air is followed by a probe
        write that must commit within probe_ticks — aborted transfers
        leave the group SERVING, not just unlatched;
      * `must_complete` transfers (falsification_transfer_plan) must
        end `completed`: the deliberately broken kernel
        (cfg.unsafe_transfer — abdicate before the target caught up,
        the §3.10 mistake) hands the election to a peer that cannot
        win it, leadership settles elsewhere, the host records an
        ABORT, and the invariant fires — proving the harness catches
        the broken kernel, not chaos in general.

    Transfer requests are retried each tick while the engine refuses
    them (no leader during a partition, latch already in flight …);
    a request refused for XFER_RETRY_TICKS straight is dropped and
    counted — refusals are load-shedding, not failures.  Fully
    deterministic: same seeded draws as the base runner, digests
    compared across runs by `make chaos-transfer`."""

    XFER_RETRY_TICKS = 60

    def __init__(self, plan, data_dir: str):
        from raftsql_tpu.chaos.invariants import TransferAvailability
        from raftsql_tpu.chaos.schedule import ChaosSchedule as _CS
        sched = _CS(seed=plan.seed, ticks=plan.ticks,
                    drops=plan.drops, partitions=plan.partitions,
                    asym_partitions=plan.asym_partitions,
                    skews=plan.skews, crashes=plan.crashes,
                    prop_rate=plan.prop_rate, read_rate=plan.read_rate)
        cfg = RaftConfig(num_groups=plan.groups, num_peers=plan.peers,
                         log_window=64, max_entries_per_msg=4,
                         election_ticks=plan.election_ticks,
                         heartbeat_ticks=1, tick_interval_s=0.0,
                         unsafe_transfer=plan.unsafe_transfer)
        super().__init__(sched, data_dir, cfg=cfg)
        self.plan = plan
        self.avail = TransferAvailability(
            election_ticks=plan.election_ticks,
            deadline_ticks=plan.deadline_ticks,
            max_stall_ticks=plan.max_stall_ticks,
            probe_ticks=plan.probe_ticks)
        # Plan events still waiting to be accepted by the engine.
        self._xfer_todo = list(plan.transfers)
        self._seen_events = 0       # consumed prefix of _xfer_events
        self.report.update({
            "transfers_requested": 0, "transfers_completed": 0,
            "transfers_aborted": 0, "transfer_refusals": 0,
            "transfer_drops": 0, "transfer_probes": 0,
            "transfer_probes_confirmed": 0, "max_transfer_stall": 0,
        })

    # -- transfer issuance ----------------------------------------------

    def _resolve_event(self, ev) -> Optional[Tuple[int, int]]:
        """(group, target) for a plan event, or None to retry later.
        target -1 = the leader's successor slot; XFER_LAGGER = the peer
        the first partition window isolated (known once the window has
        opened); group -1 = lowest group led by someone other than the
        resolved target."""
        from raftsql_tpu.chaos.schedule import XFER_LAGGER
        node = self.node
        target = ev.target
        if target == XFER_LAGGER:
            lag = self._part_peer.get(0)
            if lag is None:
                return None          # window not open yet: retry
            target = lag
        group = ev.group
        if group < 0:
            for g in range(self.cfg.num_groups):
                lead = node.leader_of(g)
                if lead >= 0 and lead != target:
                    group = g
                    break
            else:
                return None          # leaderless everywhere: retry
        if target < 0:               # successor slot
            lead = node.leader_of(group)
            if lead < 0:
                return None
            target = (lead + 1) % self.cfg.num_peers
        return group, target

    def _drive_transfers(self, t: int) -> None:
        from raftsql_tpu.runtime.node import TransferRefused
        keep = []
        for ev in self._xfer_todo:
            if ev.tick > t:
                keep.append(ev)
                continue
            if t - ev.tick > self.XFER_RETRY_TICKS:
                self.report["transfer_drops"] += 1
                continue
            resolved = self._resolve_event(ev)
            if resolved is None:
                keep.append(ev)
                continue
            group, target = resolved
            try:
                self.node.transfer_leadership(
                    group, target,
                    deadline_ticks=self.plan.deadline_ticks)
            except TransferRefused:
                self.report["transfer_refusals"] += 1
                keep.append(ev)
                continue
            self.report["transfers_requested"] += 1
            self.avail.note_issued(t, group, ev.must_complete)
        self._xfer_todo = keep

    def _apply_faults(self, t: int, rng: np.random.Generator) -> None:
        super()._apply_faults(t, rng)
        self._drive_transfers(t)

    # -- outcome absorption + serving probes ----------------------------

    def _quiet(self, t0: int, t1: int) -> bool:
        """No scheduled fault overlaps [t0, t1) — a probe armed here
        has clean air to commit in."""
        if t1 >= self.sched.ticks:
            return False
        for w in (self.sched.drops + self.sched.delays
                  + self.sched.partitions + self.sched.asym_partitions
                  + self.sched.skews):
            if w.start < t1 and t0 < w.end:
                return False
        return all(not t0 <= ev.tick < t1 for ev in self.sched.crashes)

    def _apply(self, g: int, idx: int, payload: bytes) -> None:
        super()._apply(g, idx, payload)
        parts = payload.decode("utf-8").split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            self.avail.probe_committed(parts[2])

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1) -> None:
        # Latches and the outcome log die with the process: outstanding
        # transfers are void, and the new node's event log starts empty.
        self.avail.note_crash()
        self._seen_events = 0
        super()._crash_restart(tick, power_loss, tear_peer)

    def _observe(self, t: int) -> None:
        super()._observe(t)
        events = list(self.node._xfer_events)
        for e in events[self._seen_events:]:
            self.avail.note_outcome(t, e["group"], e["outcome"],
                                    e["stall_ticks"])
            if e["outcome"] == "completed":
                self.report["transfers_completed"] += 1
            else:
                self.report["transfers_aborted"] += 1
            # Post-resolution serving probe: only in clean air — under
            # an active fault window a slow commit is the fault's
            # doing, not the transfer's.
            g = e["group"]
            if self._quiet(t, t + self.plan.probe_ticks + 1):
                value = f"v{self._wseq}"
                self._wseq += 1
                self.lin.begin_write(f"k{g}", value)
                self.node.propose_many(g, [f"SET k{g} {value}".encode()])
                self.avail.arm_probe(t, g, value)
                self.report["transfer_probes"] += 1
        self._seen_events = len(events)
        self.report["max_transfer_stall"] = self.avail.max_stall
        self.report["transfer_probes_confirmed"] = \
            self.avail.probes_confirmed
        self.avail.check(t)
        if t == self.sched.ticks - 1:
            self.avail.final_check(t)

    def _report(self) -> dict:
        r = super()._report()
        r["plan_digest"] = self.plan.digest()
        return r


def schedule_peers(schedule: ChaosSchedule) -> int:
    """Peer count implied by a schedule's targets (min 3)."""
    peers = 3
    for w in schedule.partitions:
        peers = max(peers, w.peer + 1)
    for w in schedule.asym_partitions:
        peers = max(peers, w.src + 1, w.dst + 1)
    for w in schedule.skews:
        peers = max(peers, len(w.incs))
    for ev in schedule.crashes:
        peers = max(peers, ev.tear_peer + 1)
    for f in schedule.fsync_faults:
        peers = max(peers, f.peer + 1)
    for f in schedule.enospc_faults:
        peers = max(peers, f.peer + 1)
    for f in schedule.fsync_stalls:
        peers = max(peers, f.peer + 1)
    return peers


class NodeClusterChaosRunner:
    """Lockstep RaftNode cluster under a NodeChaosPlan.

    P RaftNodes over the loopback transport, ticked manually in id
    order (deterministic consensus schedule; envelope ids randomize WAL
    bytes but not the schedule).  Faults: FaultPlan partitions,
    per-node hard crash + restart-from-WAL, leader-targeted kills.
    Invariants: election safety, per-node commit-stream durability
    across restart, and cross-node log matching of live-published
    (committed) entries.
    """

    def __init__(self, plan: NodeChaosPlan, tmpdir: str,
                 cfg: Optional[RaftConfig] = None, peers: int = 3):
        self.plan = plan
        self.tmpdir = tmpdir
        self.P = peers
        self.cfg = cfg or RaftConfig(
            num_groups=2, num_peers=peers, log_window=64,
            max_entries_per_msg=4, election_ticks=10, heartbeat_ticks=1,
            tick_interval_s=0.0)
        self.hub = LoopbackHub()
        self.nodes: List[Optional[RaftNode]] = [None] * peers
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(peers, self.cfg.num_groups)
        # Live-published (committed) history, shared: (g, idx) -> sql.
        self._hist: Dict[Tuple[int, int], str] = {}
        # Per node: everything IT has published live (must survive its
        # own restarts).
        self._published: List[Dict[Tuple[int, int], str]] = [
            {} for _ in range(peers)]
        self.report = {"crashes": 0, "restarts": 0, "partitions": 0,
                       "asym_partitions": 0, "skew_ticks": 0,
                       "corrupt_frames": 0, "commits": 0}
        self._asym_src: Dict[int, int] = {}
        # Peer slots that start UNBOOTED (provisioned spare capacity,
        # membership plans): slot -> first boot tick.  The restart path
        # then boots them fresh — "a new machine joins".
        self._initial_down: Dict[int, int] = {}
        self._t = 0
        # Wire-corruption seam: mangle encoded frames during the plan's
        # corruption windows; the CRC framing must catch every mangled
        # frame (hub.on_corrupt charges the receiving node's metrics).
        # The rng draws per route call, which is deterministic here —
        # the lockstep tick order serializes every send.
        if plan.corruptions:
            rng_c = np.random.default_rng(plan.seed + 3)

            def _mangle(src: int, dst: int, blob: bytes) -> bytes:
                for w in self.plan.corruptions:
                    if w.start <= self._t < w.end \
                            and rng_c.random() < w.p:
                        i = int(rng_c.integers(0, len(blob)))
                        return blob[:i] + bytes([blob[i] ^ 0x5A]) \
                            + blob[i + 1:]
                return blob

            self.hub.mangler = _mangle
            self.hub.on_corrupt = self._note_corrupt

    def _note_corrupt(self, src: int, dst: int) -> None:
        self.report["corrupt_frames"] += 1
        n = self.nodes[dst - 1]
        if n is not None:
            n.metrics.faults_corrupt_frames += 1

    # Subclass hooks (SnapshotChaosRunner): replay observation, per-tick
    # work (compaction cadence), commit application, final invariants.
    def _on_replay(self, p: int,
                   replayed: Dict[Tuple[int, int], str],
                   node: RaftNode) -> None:
        pass

    def _apply_commit(self, p: int, g: int, idx: int, sql: str) -> None:
        pass

    def _pre_tick(self, t: int, healing: bool,
                  rng: np.random.Generator) -> None:
        pass

    def _post_tick(self, t: int, healing: bool) -> None:
        pass

    def _settled(self) -> bool:
        """Post-heal quiescence probe for the bounded settle loop (see
        run()): True once in-flight apply pipelines have drained.  The
        base runner has no apply plane to wait on."""
        return True

    def _final_check(self) -> None:
        pass

    def _data_dir(self, p: int) -> str:
        return os.path.join(self.tmpdir, f"chaos-node-{p + 1}")

    def _boot(self, p: int) -> RaftNode:
        n = RaftNode(p + 1, self.P, self.cfg,
                     LoopbackTransport(self.hub), self._data_dir(p))
        n.enable_tracing()          # flight-recorder feed (host spans)
        n.start(threaded=False)
        # Replay drain: every WAL entry then the nil sentinel
        # (raft.go:122-134).  Verify durability of everything this node
        # ever acked; do NOT fold replay into the shared history —
        # replay includes uncommitted entries that may legally be
        # conflict-truncated later.
        replayed: Dict[Tuple[int, int], str] = {}
        while True:
            try:
                item = n.commit_q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                break
            if item is CLOSED:
                break
            for (g, idx, sql) in _expand_commit_item(item, n):
                replayed[(g, idx)] = sql
        for (g, idx), sql in self._published[p].items():
            if idx <= n.payload_log.start(g):
                # Compacted away before the crash: the entry lives on in
                # the state-machine snapshot the compaction was gated on
                # (the SnapshotChaosRunner's SM carries it; replay
                # legitimately starts above the floor).
                continue
            got = replayed.get((g, idx))
            if got != sql:
                raise InvariantViolation(
                    f"node {p}: committed entry g{g} i{idx} "
                    f"{'lost' if got is None else 'changed'} across "
                    f"restart")
        self._on_replay(p, replayed, n)
        return n

    def _resolve(self, peer: int) -> int:
        if peer != LEADER_TARGET:
            return peer
        for n in self.nodes:
            if n is not None and n.leader_of(0) >= 0:
                return int(n.leader_of(0))
        return 0

    def _drain_live(self) -> None:
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            while True:
                try:
                    item = n.commit_q.get_nowait()
                except queue.Empty:
                    break
                if item is None or item is CLOSED:
                    continue
                for (g, idx, sql) in _expand_commit_item(item, n):
                    prev = self._hist.setdefault((g, idx), sql)
                    if prev != sql:
                        raise InvariantViolation(
                            f"log matching: node {p} committed g{g} "
                            f"i{idx} {sql!r} but {prev!r} was committed")
                    self._published[p][(g, idx)] = sql
                    self._apply_commit(p, g, idx, sql)
                    self.report["commits"] += 1

    def _observe(self, t: int) -> None:
        G = self.cfg.num_groups
        roles = np.full((self.P, G), DEAD_ROLE, np.int64)
        terms = np.zeros((self.P, G), np.int64)
        commits = np.zeros((self.P, G), np.int64)
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            roles[p] = n._last_role
            terms[p] = n._hard_np[:, 0]
            commits[p] = n._hard_np[:, 2]
        self.safety.observe(t, roles, terms)
        # Dead rows read 0 — mask them to each node's running floor so
        # a down node never looks like a regression.
        commits = np.maximum(commits, self.monotonic._hi * (roles < 0))
        self.monotonic.observe(t, commits)

    def run(self) -> dict:
        inj = fsio.StorageFaultInjector()   # no rules: forces the
        rng = np.random.default_rng(self.plan.seed + 1)  # python WAL
        crash_at: Dict[int, list] = {}
        for c in self.plan.crashes:
            crash_at.setdefault(c.tick, []).append(c)
        down_until: Dict[int, int] = {}
        total = self.plan.ticks + self.plan.heal_ticks
        with fsio.installed(inj):
            for p in range(self.P):
                if p not in self._initial_down:
                    self.nodes[p] = self._boot(p)
            try:
                for t in range(total):
                    self._t = t
                    # The heal window: no new faults, no new load —
                    # in-flight recovery (restarts, transfers) finishes
                    # and the survivors must converge (_final_check).
                    healing = t >= self.plan.ticks
                    for c in crash_at.get(t, ()):
                        p = self._resolve(c.peer)
                        if self.nodes[p] is None:
                            continue
                        hard_crash_node(self.nodes[p])
                        self.nodes[p] = None
                        down_until[p] = t + c.down
                        self.report["crashes"] += 1
                    for p in [p for p, d in down_until.items()
                              if d <= t]:
                        del down_until[p]
                        self.nodes[p] = self._boot(p)
                        self.report["restarts"] += 1
                    for p in [p for p, bt in self._initial_down.items()
                              if bt <= t]:
                        # Provisioned spare slot comes online: a FRESH
                        # machine (empty WAL) joining the cluster.
                        del self._initial_down[p]
                        self.nodes[p] = self._boot(p)
                        self.report["boots"] = \
                            self.report.get("boots", 0) + 1
                    self.hub.faults.heal()
                    incs: Optional[Tuple[int, ...]] = None
                    if not healing:
                        for w in self.plan.partitions:
                            if w.start <= t < w.end:
                                if t == w.start:
                                    self.report["partitions"] += 1
                                self.hub.faults.isolate(
                                    w.peer + 1, range(1, self.P + 1))
                        for wi, w in enumerate(self.plan.asym_partitions):
                            if w.start <= t < w.end:
                                src = self._asym_src.get(wi)
                                if src is None:
                                    src = self._resolve(w.src)
                                    self._asym_src[wi] = src
                                    self.report["asym_partitions"] += 1
                                self.hub.faults.block(src + 1, w.dst + 1)
                        for w in self.plan.skews:
                            if w.start <= t < w.end:
                                incs = w.incs
                    # Subclass seam (membership runner: seeded per-link
                    # drops, scripted admin churn).  Draw order is fixed,
                    # so determinism survives the hook.
                    self._pre_tick(t, healing, rng)
                    if not healing:
                        if rng.random() < self.plan.prop_rate:
                            alive = [p for p, n in enumerate(self.nodes)
                                     if n is not None]
                            src = alive[int(rng.integers(0, len(alive)))]
                            g = int(rng.integers(0, self.cfg.num_groups))
                            self.nodes[src].propose(
                                g, f"SET k{g} v{t}".encode())
                    for p, n in enumerate(self.nodes):
                        if n is None:
                            continue
                        inc = 1 if incs is None else int(incs[p])
                        if inc != 1:
                            self.report["skew_ticks"] += abs(inc - 1)
                            n.metrics.faults_skew_ticks += abs(inc - 1)
                        n.tick(timer_inc=inc)
                    self._drain_live()
                    self._observe(t)
                    self._post_tick(t, healing)
                # Bounded settle: the heal window can end on the very
                # tick the leader commits its last entry, leaving the
                # followers' applied indexes a publish batch behind
                # (the PR-12 batched commit stream delivers on the NEXT
                # tick).  Tick fault-free until the subclass reports
                # quiescence — deterministic (no load, no rng draws)
                # and bounded, so a peer that never catches up still
                # fails `_final_check` loudly instead of hanging.
                settle = 0
                while settle < SETTLE_TICKS_MAX and not self._settled():
                    self.hub.faults.heal()
                    for n in self.nodes:
                        if n is not None:
                            n.tick()
                    self._drain_live()
                    self._observe(total + settle)
                    settle += 1
                self.report["settle_ticks"] = settle
                self._final_check()
            except InvariantViolation as e:
                self._flight_dump(e)
                raise
            finally:
                for n in self.nodes:
                    if n is not None:
                        n.stop()
        return {"plan_digest": self.plan.digest(),
                "result_digest": self._result_digest(), **self.report}

    def _flight_dump(self, err: Exception) -> None:
        """Host-plane flight dump (this plane has no device ring): the
        first live node's spans, next to the failing seed."""
        from raftsql_tpu.obs.flight import FlightRecorder
        tracer = next((n.tracer for n in self.nodes if n is not None),
                      None)
        FlightRecorder().dump(
            f"node-seed{self.plan.seed}", repr(err), tracer=tracer,
            meta={"seed": self.plan.seed,
                  "plan_digest": self.plan.digest(),
                  "report": dict(self.report)})

    def _result_digest(self) -> str:
        """Digest of the run's committed (unwrapped) history + fault
        counts: identical across two runs of one plan — envelope ids
        randomize WAL bytes but never the lockstep schedule or the
        decoded commit stream."""
        hist = sorted((g, i, s) for (g, i), s in self._hist.items())
        blob = json.dumps({"hist": hist, "report": self.report},
                          sort_keys=True, separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


class SnapshotChaosRunner(NodeClusterChaosRunner):
    """Aggressive compaction + InstallSnapshot + crash interleavings.

    Each node carries a tiny per-group KV state machine applied from
    its commit stream (this runner IS the apply plane), exposed through
    the node's snapshot provider/installer hooks as a JSON blob, and
    compacts its own log on the plan's cadence gated on its own applied
    index — the RaftDB calling convention (runtime/db.py).  The plan
    crashes one follower long enough that every retained log floor
    passes it by: its restart can only be served by a full state
    transfer, while a second (leader-targeted) crash lands after the
    transfer window.  After the fault-free heal window the survivors
    must CONVERGE — same applied index, identical state, the installed
    peer included (chaos/invariants.py check_convergence); this is the
    check log matching cannot give once the log below a floor is gone.
    """

    def __init__(self, plan: NodeChaosPlan, tmpdir: str, peers: int = 3):
        cfg = RaftConfig(num_groups=2, num_peers=peers, log_window=16,
                         max_entries_per_msg=4, election_ticks=10,
                         heartbeat_ticks=1, tick_interval_s=0.0)
        super().__init__(plan, tmpdir, cfg=cfg, peers=peers)
        G = self.cfg.num_groups
        self._sm: List[List[Dict[str, str]]] = [
            [dict() for _ in range(G)] for _ in range(peers)]
        self._sm_applied = np.zeros((peers, G), np.int64)
        self.report.update({"snapshots_installed": 0,
                            "snapshots_sent": 0, "compactions": 0})

    def _boot(self, p: int) -> RaftNode:
        n = super()._boot(p)
        n.snapshot_provider = lambda g, p=p: self._provide(p, g)
        n.snapshot_installer = \
            lambda g, idx, blob, p=p: self._install(p, g, idx, blob)
        return n

    def _on_replay(self, p: int, replayed, node: RaftNode) -> None:
        # The crash took the SM with it (these dicts ARE the apply
        # plane): rebuild from the replay stream, exactly as RaftDB's
        # delete-and-replay does (reference db.go:27-29).
        G = self.cfg.num_groups
        self._sm[p] = [dict() for _ in range(G)]
        self._sm_applied[p] = 0
        for (g, idx) in sorted(replayed):
            self._apply_sm(p, g, idx, replayed[(g, idx)])

    def _apply_commit(self, p: int, g: int, idx: int, sql: str) -> None:
        self._apply_sm(p, g, idx, sql)

    def _apply_sm(self, p: int, g: int, idx: int, sql: str) -> None:
        parts = sql.split(" ")
        if len(parts) == 3 and parts[0] == "SET":
            self._sm[p][g][parts[1]] = parts[2]
        if idx > self._sm_applied[p, g]:
            self._sm_applied[p, g] = idx

    def _provide(self, p: int, g: int):
        blob = json.dumps(sorted(self._sm[p][g].items())).encode()
        return int(self._sm_applied[p, g]), blob

    def _install(self, p: int, g: int, idx: int, blob: bytes) -> None:
        self._sm[p][g] = dict(json.loads(blob.decode()))
        self._sm_applied[p, g] = idx
        self.report["snapshots_installed"] += 1

    def _post_tick(self, t: int, healing: bool) -> None:
        ce = self.plan.compact_every
        if not ce or not t or t % ce:
            return
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            applied = {g: int(self._sm_applied[p, g])
                       for g in range(self.cfg.num_groups)}
            if n.compact(applied, keep=self.plan.compact_keep):
                self.report["compactions"] += 1

    def _settled(self) -> bool:
        """Quiesced once every group's survivors agree on the applied
        index — the state-identity half of convergence is then
        `_final_check`'s to judge (a snapshot that installed WRONG
        state converges in index and still fails there)."""
        for g in range(self.cfg.num_groups):
            tops = {int(self._sm_applied[p, g])
                    for p, n in enumerate(self.nodes) if n is not None}
            if len(tops) > 1:
                return False
        return True

    def _final_check(self) -> None:
        self.report["snapshots_sent"] = sum(
            n.metrics.snapshots_sent for n in self.nodes
            if n is not None)
        for g in range(self.cfg.num_groups):
            survivors = [(p, int(self._sm_applied[p, g]), self._sm[p][g])
                         for p, n in enumerate(self.nodes)
                         if n is not None]
            check_convergence(g, survivors, context="post-heal")


def _free_ports(n: int) -> List[int]:
    """n OS-assigned localhost ports (bind-and-release; the runs bind
    them back immediately, and a collision fails loudly on bind)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class TcpClusterChaosRunner:
    """Chaos under the REAL TCP transport (transport/tcp.py).

    P RaftNodes ticked manually, but their frames cross actual
    localhost sockets through each transport's SendFaults seam: seeded
    send-side drops, ONE-directional blocks (asymmetric partition),
    frame corruption (the receiver's CRC framing must drop + count
    every mangled frame and keep its recv loop alive), and delayed
    frames (out-of-order arrival).  Kernel scheduling orders delivery,
    so this plane is NOT bit-reproducible — the schedule is
    deterministic from the seed and the invariants (election safety,
    commit monotonicity, cross-node log matching of the published
    streams) must hold on every run, which is exactly the guarantee a
    real deployment gets.  After the heal window the cluster must have
    made real progress (commits floor asserted by callers).
    """

    def __init__(self, plan: TcpChaosPlan, tmpdir: str, peers: int = 3):
        self.plan = plan
        self.tmpdir = tmpdir
        self.P = peers
        self.cfg = RaftConfig(
            num_groups=2, num_peers=peers, log_window=64,
            max_entries_per_msg=4, election_ticks=10, heartbeat_ticks=1,
            tick_interval_s=0.0)
        self.nodes: List[Optional[RaftNode]] = [None] * peers
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(peers, self.cfg.num_groups)
        self._hist: Dict[Tuple[int, int], str] = {}
        self.report = {"commits": 0, "sent_dropped": 0,
                       "sent_corrupted": 0, "sent_delayed": 0,
                       "corrupt_frames_dropped": 0, "asym_partitions": 0}

    def _drain_live(self) -> None:
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            while True:
                try:
                    item = n.commit_q.get_nowait()
                except queue.Empty:
                    break
                if item is None or item is CLOSED:
                    continue
                for (g, idx, sql) in _expand_commit_item(item, n):
                    prev = self._hist.setdefault((g, idx), sql)
                    if prev != sql:
                        raise InvariantViolation(
                            f"log matching: node {p} committed g{g} "
                            f"i{idx} {sql!r} but {prev!r} was committed")
                    self.report["commits"] += 1

    def _observe(self, t: int) -> None:
        G = self.cfg.num_groups
        roles = np.full((self.P, G), DEAD_ROLE, np.int64)
        terms = np.zeros((self.P, G), np.int64)
        commits = np.zeros((self.P, G), np.int64)
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            roles[p] = n._last_role
            terms[p] = n._hard_np[:, 0]
            commits[p] = n._hard_np[:, 2]
        self.safety.observe(t, roles, terms)
        commits = np.maximum(commits, self.monotonic._hi * (roles < 0))
        self.monotonic.observe(t, commits)

    def run(self) -> dict:
        ports = _free_ports(self.P)
        urls = [f"127.0.0.1:{port}" for port in ports]
        faults = [SendFaults(self.plan.seed * 131 + p)
                  for p in range(self.P)]
        rng = np.random.default_rng(self.plan.seed + 1)
        try:
            for p in range(self.P):
                tr = TcpTransport(urls, p)
                tr.faults = faults[p]
                n = RaftNode(p + 1, self.P, self.cfg, tr,
                             os.path.join(self.tmpdir,
                                          f"tcp-node-{p + 1}"))
                n.start(threaded=False)
                self.nodes[p] = n
            total = self.plan.ticks + self.plan.heal_ticks
            for t in range(total):
                healing = t >= self.plan.ticks
                for p, f in enumerate(faults):
                    f.heal()
                    drop = corrupt = delay = dsec = 0.0
                    if not healing:
                        for w in self.plan.drops:
                            if w.start <= t < w.end:
                                drop = w.p
                        for w in self.plan.corruptions:
                            if w.start <= t < w.end:
                                corrupt = w.p
                        for w in self.plan.delays:
                            if w.start <= t < w.end:
                                delay = w.p
                                dsec = w.latency / 1000.0
                        for w in self.plan.asym_partitions:
                            if w.start <= t < w.end and p == w.src:
                                f.block(w.dst + 1)
                                if t == w.start:
                                    self.report["asym_partitions"] += 1
                    f.set_rates(drop, corrupt, delay, dsec)
                if not healing and rng.random() < self.plan.prop_rate:
                    g = int(rng.integers(0, self.cfg.num_groups))
                    src = int(rng.integers(0, self.P))
                    self.nodes[src].propose(g, f"SET k{g} v{t}".encode())
                for n in self.nodes:
                    n.tick()
                # Let frames cross the sockets before the next tick:
                # the recv threads stage asynchronously.
                time.sleep(0.002)
                self._drain_live()
                self._observe(t)
        finally:
            for n in self.nodes:
                if n is not None:
                    n.stop()
        self.report["sent_dropped"] = sum(f.dropped for f in faults)
        self.report["sent_corrupted"] = sum(f.corrupted for f in faults)
        self.report["sent_delayed"] = sum(f.delayed for f in faults)
        self.report["corrupt_frames_dropped"] = sum(
            n.metrics.faults_corrupt_frames for n in self.nodes
            if n is not None)
        return {"plan_digest": self.plan.digest(), **self.report}


class MembershipChaosRunner(NodeClusterChaosRunner):
    """Dynamic-membership churn under faults (raftsql_tpu/membership/).

    The node-replacement story, scripted by a MembershipChaosPlan: a
    cluster booted on `initial_voters` over P provisioned slots loses a
    voter to a permanent SIGKILL, boots a spare slot as a FRESH machine
    (empty WAL), adds it as a learner, promotes it through joint
    consensus once caught up, and removes the dead member — while
    drops, partitions, and transient crashes land mid-churn.  Admin ops
    are issued against the group's current leader and retried every
    tick until the applied configuration reflects them (exactly an
    operator's retry loop, including aborting a change whose entry was
    lost with its leader).

    On top of the base invariants (single leader per term, per-node
    durability across restart, log matching, commit monotonicity) every
    tick observes RemovedQuorumSafety — no quorum from a removed
    majority — and the final check asserts every live node converged on
    `plan.final_voters` with zero learners AND that the cluster still
    commits on the post-churn configuration.  Fully deterministic
    (lockstep ticks, seeded draws): two runs of one plan must produce
    identical result digests.
    """

    # Abort-and-reissue horizon for an admin op whose conf entry was
    # lost (leader died holding the one-in-flight latch, proposal
    # dropped): an operator timeout, in ticks.
    RETRY_TICKS = 60

    def __init__(self, plan: MembershipChaosPlan, tmpdir: str):
        cfg = RaftConfig(
            num_groups=2, num_peers=plan.peers, log_window=64,
            max_entries_per_msg=4, election_ticks=10, heartbeat_ticks=1,
            tick_interval_s=0.0, initial_voters=plan.initial_voters)
        super().__init__(plan, tmpdir, cfg=cfg, peers=plan.peers)
        for b in plan.boots:
            self._initial_down[b.peer] = b.tick
        self.removed_safety = RemovedQuorumSafety(LEADER)
        self._events = sorted(plan.events, key=lambda e: e.tick)
        G = self.cfg.num_groups
        self._ev_done = [0] * G          # per-group next-event cursor
        # g -> (node the pending op was issued at, issue tick).
        self._issued: Dict[int, Tuple[int, int]] = {}
        # report["commits"] at the moment every group settled on the
        # final config — progress after this point proves the new
        # voter set actually commits.
        self._settle_commits: Optional[int] = None
        self.report.update({"boots": 0, "member_ops_applied": 0,
                            "member_op_retries": 0,
                            "member_op_aborts": 0})

    # -- scripted admin churn ------------------------------------------

    def _op_complete(self, g: int, op: str, peer: int) -> bool:
        """The applied config of some live node reflects the op and the
        group left its joint state (replication spreads it from there;
        the next op validates against the leader's view anyway)."""
        for n in self.nodes:
            if n is None or n.membership is None:
                continue
            c = n.membership.config(g)
            if c.is_joint:
                continue
            bit = 1 << peer
            if op == "add_learner" and c.learners & bit:
                return True
            if op == "promote" and c.voters & bit \
                    and not c.learners & bit:
                return True
            if op == "remove" and c.index > 0 \
                    and not (c.voters | c.joint) & bit:
                return True
            if op == "remove_learner" and c.index > 0 \
                    and not c.learners & bit:
                return True
        return False

    def _leader_node(self, g: int) -> Optional[int]:
        for p, n in enumerate(self.nodes):
            if n is not None and n._last_role[g] == LEADER:
                return p
        return None

    def _drive_events(self, t: int) -> None:
        from raftsql_tpu.membership import MembershipError
        for g in range(self.cfg.num_groups):
            i = self._ev_done[g]
            if i >= len(self._events):
                continue
            ev = self._events[i]
            if t < ev.tick:
                continue
            if self._op_complete(g, ev.op, ev.peer):
                self._ev_done[g] += 1
                self._issued.pop(g, None)
                self.report["member_ops_applied"] += 1
                continue
            lead = self._leader_node(g)
            if lead is None:
                continue
            try:
                self.nodes[lead].member_change(g, ev.op, ev.peer)
                self._issued[g] = (lead, t)
            except MembershipError:
                # Not caught up yet / change in flight / transient
                # joint state: the operator retry loop.  If the latch
                # holder sat on an in-flight change past the horizon
                # (its conf entry died with a deposed leader), abort it
                # there and reissue fresh.
                self.report["member_op_retries"] += 1
                src_t = self._issued.get(g)
                if src_t is not None \
                        and t - src_t[1] > self.RETRY_TICKS:
                    src = self.nodes[src_t[0]]
                    if src is not None and src.membership is not None:
                        src.membership.abort_pending(g)
                        self.report["member_op_aborts"] += 1
                    self._issued[g] = (src_t[0], t)

    def _pre_tick(self, t: int, healing: bool,
                  rng: np.random.Generator) -> None:
        if not healing:
            # Per-link drop windows: the loopback hub has no rate seam,
            # so each active window blocks a seeded subset of directed
            # links for THIS tick (heal() lifts them next tick).  Draw
            # count per tick is fixed — determinism holds.
            for w in self.plan.drops:
                if w.start <= t < w.end:
                    for s in range(self.P):
                        for d in range(self.P):
                            if s != d and rng.random() < w.p:
                                self.hub.faults.block(s + 1, d + 1)
        self._drive_events(t)
        if healing and self._needs_settle_load():
            # Keep a trickle of writes flowing until the post-churn
            # config has demonstrably committed (the heal window's
            # no-new-load rule bends exactly this far: proving the
            # final voter set commits IS the recovery being waited on).
            for g in range(self.cfg.num_groups):
                lead = self._leader_node(g)
                if lead is not None:
                    self.nodes[lead].propose(
                        g, f"SET settle{g} t{t}".encode())

    def _needs_settle_load(self) -> bool:
        return self._settle_commits is None \
            or self.report["commits"] <= self._settle_commits + 5

    # -- invariants ----------------------------------------------------

    def _final_mask(self) -> int:
        want = 0
        for v in self.plan.final_voters:
            want |= 1 << v
        return want

    def _post_tick(self, t: int, healing: bool) -> None:
        if self._settle_commits is not None:
            return
        if any(i < len(self._events) for i in self._ev_done):
            return
        want = self._final_mask()
        for n in self.nodes:
            if n is None or n.membership is None:
                continue
            for g in range(self.cfg.num_groups):
                c = n.membership.config(g)
                if c.is_joint or c.voters != want:
                    return
        self._settle_commits = self.report["commits"]

    def _observe(self, t: int) -> None:
        super()._observe(t)
        G = self.cfg.num_groups
        roles = np.full((self.P, G), DEAD_ROLE, np.int64)
        for p, n in enumerate(self.nodes):
            if n is not None:
                roles[p] = n._last_role

        def voter_of(p: int, g: int) -> bool:
            n = self.nodes[p]
            return n is not None and n.membership is not None \
                and bool(n.membership.voter_mask(g) >> p & 1)

        live = [n.membership.voter_mask for n in self.nodes
                if n is not None and n.membership is not None]
        self.removed_safety.observe(t, roles, voter_of, live)

    def _final_check(self) -> None:
        want = self._final_mask()
        for g in range(self.cfg.num_groups):
            for p, n in enumerate(self.nodes):
                if n is None or n.membership is None:
                    continue
                c = n.membership.config(g)
                if c.is_joint or c.voters != want or c.learners:
                    raise InvariantViolation(
                        f"post-heal g={g}: node {p} ended on "
                        f"voters={c.voters:#x} joint={c.is_joint} "
                        f"learners={c.learners:#x}, wanted "
                        f"voters={want:#x} stable")
        if self._settle_commits is None:
            raise InvariantViolation(
                "the scripted membership churn never completed: "
                f"per-group event cursors {self._ev_done} of "
                f"{len(self._events)}")
        if self.report["commits"] <= self._settle_commits:
            raise InvariantViolation(
                "no commits observed on the post-churn configuration "
                f"(stuck at {self._settle_commits})")


class TcpRebindChaosRunner:
    """TCP-plane crash/restart with PORT REBINDING (the ROADMAP chaos
    frontier item): a TcpRebindPlan stops nodes — their listeners
    close, their ports are released — and restarts each on the SAME
    port and data dir `down` ticks later.  Peers' sender threads must
    reconnect through their backoff loop, the rebound listener must
    accept them, and the restarted node must catch up on everything
    committed while it was away.  Same reproducibility posture as
    TcpClusterChaosRunner: the schedule is deterministic from the
    seed, the invariants (election safety, commit monotonicity, log
    matching of published streams) must hold on every run, but
    kernel-scheduled arrival keeps the history non-bit-reproducible.
    """

    def __init__(self, plan: TcpRebindPlan, tmpdir: str, peers: int = 3):
        self.plan = plan
        self.tmpdir = tmpdir
        self.P = peers
        self.cfg = RaftConfig(
            num_groups=2, num_peers=peers, log_window=64,
            max_entries_per_msg=4, election_ticks=10, heartbeat_ticks=1,
            tick_interval_s=0.0)
        self.nodes: List[Optional[RaftNode]] = [None] * peers
        self.safety = ElectionSafety(LEADER)
        self.monotonic = CommitMonotonic(peers, self.cfg.num_groups)
        self._hist: Dict[Tuple[int, int], str] = {}
        self._urls: List[str] = []
        self.report = {"commits": 0, "stops": 0, "rebinds": 0}

    def _boot(self, p: int) -> RaftNode:
        tr = TcpTransport(self._urls, p)
        n = RaftNode(p + 1, self.P, self.cfg, tr,
                     os.path.join(self.tmpdir, f"rebind-node-{p + 1}"))
        n.start(threaded=False)
        return n

    def _resolve(self, peer: int) -> int:
        if peer != LEADER_TARGET:
            return peer
        for n in self.nodes:
            if n is not None and n.leader_of(0) >= 0:
                return int(n.leader_of(0))
        return 0

    def _drain_live(self) -> None:
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            while True:
                try:
                    item = n.commit_q.get_nowait()
                except queue.Empty:
                    break
                if item is None or item is CLOSED:
                    continue
                for (g, idx, sql) in _expand_commit_item(item, n):
                    prev = self._hist.setdefault((g, idx), sql)
                    if prev != sql:
                        raise InvariantViolation(
                            f"log matching: node {p} committed g{g} "
                            f"i{idx} {sql!r} but {prev!r} was committed")
                    self.report["commits"] += 1

    def _observe(self, t: int) -> None:
        G = self.cfg.num_groups
        roles = np.full((self.P, G), DEAD_ROLE, np.int64)
        terms = np.zeros((self.P, G), np.int64)
        commits = np.zeros((self.P, G), np.int64)
        for p, n in enumerate(self.nodes):
            if n is None:
                continue
            roles[p] = n._last_role
            terms[p] = n._hard_np[:, 0]
            commits[p] = n._hard_np[:, 2]
        self.safety.observe(t, roles, terms)
        commits = np.maximum(commits, self.monotonic._hi * (roles < 0))
        self.monotonic.observe(t, commits)

    def run(self) -> dict:
        ports = _free_ports(self.P)
        self._urls = [f"127.0.0.1:{port}" for port in ports]
        rng = np.random.default_rng(self.plan.seed + 1)
        restart_at: Dict[int, list] = {}
        for c in self.plan.restarts:
            restart_at.setdefault(c.tick, []).append(c)
        down_until: Dict[int, int] = {}
        total = self.plan.ticks + self.plan.heal_ticks
        try:
            for p in range(self.P):
                self.nodes[p] = self._boot(p)
            for t in range(total):
                healing = t >= self.plan.ticks
                for c in restart_at.get(t, ()):
                    p = self._resolve(c.peer)
                    if self.nodes[p] is None:
                        continue
                    # Graceful stop: the listener closes and the PORT
                    # IS RELEASED (crash-without-rebind is the node
                    # runner's family; this one is about the rebind).
                    self.nodes[p].stop()
                    self.nodes[p] = None
                    down_until[p] = t + c.down
                    self.report["stops"] += 1
                for p in [p for p, d in down_until.items() if d <= t]:
                    del down_until[p]
                    # Same port, same data dir: replay-from-WAL, then
                    # peers reconnect into the rebound listener.
                    self.nodes[p] = self._boot(p)
                    self.report["rebinds"] += 1
                if not healing and rng.random() < self.plan.prop_rate:
                    alive = [p for p, n in enumerate(self.nodes)
                             if n is not None]
                    src = alive[int(rng.integers(0, len(alive)))]
                    g = int(rng.integers(0, self.cfg.num_groups))
                    self.nodes[src].propose(g, f"SET k{g} v{t}".encode())
                for n in self.nodes:
                    if n is not None:
                        n.tick()
                time.sleep(0.002)
                self._drain_live()
                self._observe(t)
            # Catch-up check: every node is back, and no node's commit
            # trails the cluster max by more than one append batch
            # (the last heartbeat's commit broadcast may be in flight).
            commits = np.stack([n._hard_np[:, 2] for n in self.nodes])
            spread = commits.max(axis=0) - commits.min(axis=0)
            if (spread > self.cfg.max_entries_per_msg).any():
                raise InvariantViolation(
                    f"post-heal catch-up failed: commit spread "
                    f"{spread.tolist()} across rebound nodes")
        finally:
            for n in self.nodes:
                if n is not None:
                    n.stop()
        return {"plan_digest": self.plan.digest(), **self.report}


class ReshardChaosRunner(FusedChaosRunner):
    """The elastic-keyspace nemesis (fused plane): seeded split/merge/
    migrate schedules race partitions, message drops, whole-cluster
    crash+restart, coordinator SIGKILL mid-verb, and disk faults on the
    snapshot ship path, under live acked-PUT load — checked by
    NoAckedWriteLost and NoAvailabilityLoss on top of the standing
    election-safety / durability / linearizability invariants.

    Keyspace model: keys hash onto `plan.nslots` slots; a shared
    `KeyMap` (reshard/keymap.py) routes each slot to a raft group and
    the workload routes writes/reads through it — frozen slots are
    refused up front (the client's 503).  Every group keeps an
    independent keyed store (`_gkv[g]`), and reads resolve against the
    SERVING group's state, so a premature router flip really does serve
    the moved keys from an empty shard.

    The reshard fence is IN the logs: the coordinator's `begin` record
    applies in the source group's own log order, and any keyed write
    applying after it on a moving slot is BOUNCED (never acked, client
    retries after the verb) — closing the late-straggler window by log
    order, not timing.  `flip` grants/`RD` range-deletes close a verb
    id per group, so a stale re-proposed copy can never resurrect rows
    a later verb deleted.

    Coordinator SIGKILL: the coordinator object is discarded mid-verb
    and a fresh one is rebuilt `coordinator_down_ticks` later from the
    journal fold alone (reshard/journal.py) — exactly what a restarted
    coordinator process would do.  Whole-cluster crashes additionally
    rebuild every `_gkv`/fence/journal from the WAL replay (the base
    runner's ledger-audited boot), and each such restart ends in the
    NoAckedWriteLost WAL-fold post-mortem when no verb is in flight.

    Fully deterministic: same seeded draws as the base runner, digests
    compared across runs by `make chaos-reshard`."""

    EXCLUSIVE_EVERY = 32      # steady-state exactly-one-owner cadence

    def __init__(self, plan, data_dir: str):
        from raftsql_tpu.chaos.invariants import (NoAckedWriteLost,
                                                  NoAvailabilityLoss)
        from raftsql_tpu.chaos.schedule import ChaosSchedule as _CS
        from raftsql_tpu.reshard import KeyMap
        sched = _CS(seed=plan.seed, ticks=plan.ticks, drops=plan.drops,
                    partitions=plan.partitions,
                    asym_partitions=plan.asym_partitions,
                    crashes=plan.crashes,
                    prop_rate=plan.prop_rate, read_rate=plan.read_rate)
        cfg = RaftConfig(num_groups=plan.groups, num_peers=plan.peers,
                         log_window=64, max_entries_per_msg=4,
                         election_ticks=plan.election_ticks,
                         heartbeat_ticks=1, tick_interval_s=0.0)
        super().__init__(sched, data_dir, cfg=cfg)
        self.KEYS = plan.keys
        self.plan = plan
        self.lost = NoAckedWriteLost()
        self.avail = NoAvailabilityLoss(plan.probe_ticks,
                                        plan.verb_deadline_ticks)
        G = plan.groups
        self._km = KeyMap.initial(G, plan.nslots)
        self._gkv: Dict[int, Dict[str, str]] = {g: {} for g in range(G)}
        self._fence: Dict[int, set] = {g: set() for g in range(G)}
        self._flipped: Dict[int, set] = {g: set() for g in range(G)}
        self._closed: Dict[int, set] = {g: set() for g in range(G)}
        self._jrecs: List[dict] = []       # decoded RJ records (dupes ok)
        self._jseen: set = set()           # (id, step, group) applied
        self._jwant: Dict[tuple, int] = {} # (id, step) -> gating group
        self.coord = None
        self._replaying = False
        self._reshard_todo = list(plan.reshards)
        self._kills = set(plan.coordinator_kills)
        self._coord_down_until = -1
        self._xfer_cursor = 0
        self._cutover_started = False
        self._presplit_done = not plan.presplit_transfer
        self._tick_now = 0
        self.report.update({
            "reshard_splits": 0, "reshard_merges": 0,
            "reshard_migrations": 0, "reshard_aborted": 0,
            "reshard_resumed": 0, "reshard_flips": 0,
            "coordinator_kills": 0, "fork_faults": 0,
            "writes_bounced": 0, "copies_discarded": 0,
            "reshard_probes": 0, "reshard_probes_confirmed": 0,
            "moved_checks": 0, "exclusive_checks": 0,
            "keymap_epoch": 0,
        })

    # -- boot / crash ---------------------------------------------------

    def _boot(self, first: bool):
        for g in range(self.cfg.num_groups):
            self._gkv[g].clear()
            self._fence[g].clear()
            self._flipped[g].clear()
            self._closed[g].clear()
        self._jrecs.clear()
        self._jseen.clear()
        self._jwant.clear()
        self.coord = None
        self._replaying = True
        try:
            node = super()._boot(first)
        finally:
            self._replaying = False
        if first and self.plan.fork_fault_op >= 0:
            inj = fsio.injector()
            if inj is not None:
                inj.add_rule(os.sep + "reshard-ship" + os.sep,
                             fail_at=(self.plan.fork_fault_op,))
        self.node = node
        self._rebuild_coordinator()
        return node

    def _rebuild_coordinator(self) -> None:
        from raftsql_tpu.reshard import ReshardCoordinator
        self.coord = ReshardCoordinator(
            self, self._km, num_groups=self.cfg.num_groups,
            broken_flip=self.plan.broken_flip,
            retry_steps=self.plan.retry_steps)
        self.coord.recover(self._jrecs)
        for ev in self.coord.drain_events():
            if ev["kind"] == "resume":
                self.report["reshard_resumed"] += 1
                self.avail.verb_started(self._tick_now, ev["id"])

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1) -> None:
        self._tick_now = tick
        self.avail.note_crash(tick)
        self._xfer_cursor = 0
        self._cutover_started = False
        super()._crash_restart(tick, power_loss, tear_peer)
        if self.coord is not None and not self.coord.busy \
                and not self._km.frozen:
            self.lost.check_exclusive(
                self._km, self._gkv,
                context=f" (WAL-fold post-mortem, restart at tick "
                        f"{tick})")
            self.report["exclusive_checks"] = self.lost.exclusive_checks

    # -- apply plane: fences + journal fold -----------------------------

    def _apply(self, g: int, idx: int, payload: bytes) -> None:
        from raftsql_tpu.reshard.journal import decode_rdel, decode_record
        from raftsql_tpu.reshard.keymap import slot_of
        self.ledger.record(g, idx, payload)
        self._applied[g] = max(self._applied[g], idx)
        text = payload.decode("utf-8", "replace")
        rec = decode_record(text)
        if rec is not None:
            vid = int(rec["id"])
            self._jrecs.append(rec)
            self._jseen.add((vid, rec["step"], g))
            slots = set(int(s) for s in rec.get("slots", ()))
            if rec.get("verb") != "migrate":
                if rec["step"] == "begin" and rec.get("src") == g:
                    self._fence[g] |= slots
                elif rec["step"] == "abort" and rec.get("src") == g:
                    self._fence[g] -= slots
                elif rec["step"] == "flip":
                    if rec.get("src") == g:
                        self._fence[g] -= slots
                        self._flipped[g] |= slots
                    if rec.get("dst") == g:
                        self._flipped[g] -= slots
                        self._closed[g].add(vid)
            return
        rd = decode_rdel(text)
        if rd is not None:
            ss = set(int(s) for s in rd["slots"])
            n = int(rd["nslots"])
            for k in [k for k in self._gkv[g]
                      if slot_of(k, n) in ss]:
                del self._gkv[g][k]
            self._closed[g].add(int(rd["id"]))
            return
        parts = text.split(" ")
        if len(parts) == 4 and parts[0] == "CPY":
            vid, key, value = int(parts[1]), parts[2], parts[3]
            if vid in self._closed[g]:
                if not self._replaying:
                    self.report["copies_discarded"] += 1
            else:
                self._gkv[g][key] = value
            return
        if len(parts) == 3 and parts[0] == "SET":
            key, value = parts[1], parts[2]
            s = slot_of(key, self.plan.nslots)
            if s in self._fence[g] or s in self._flipped[g]:
                # The write raced the reshard fence: it applied after
                # the begin/flip record in this group's OWN log order,
                # so every replica discards it identically and the
                # client is never acked (it retries at the new owner).
                if not self._replaying:
                    self.report["writes_bounced"] += 1
                return
            self._gkv[g][key] = value
            self._kv[key] = value
            self.lin.end_write(value)
            if not self._replaying:
                self.lost.note_ack(key, value)
                self.avail.probe_committed(value)

    # -- workload routed by the keymap ----------------------------------

    def _issue(self, rng: np.random.Generator) -> None:
        km = self._km
        if rng.random() < self.sched.prop_rate:
            k = int(rng.integers(0, self.KEYS))
            key = f"k{k}"
            if not km.is_frozen(key):
                g = km.group_of(key)
                value = f"v{self._wseq}"
                self._wseq += 1
                self.lin.begin_write(key, value)
                self.node.propose_many(g, [f"SET {key} {value}".encode()])
        if rng.random() < self.sched.read_rate:
            k = int(rng.integers(0, self.KEYS))
            key = f"k{k}"
            if not km.is_frozen(key):
                g = km.group_of(key)
                got = self.node.read_index(g)
                if got:
                    target, _ = got
                    self._pending_reads.append(
                        (key, g, target, self.lin.begin_read(key)))

    def _resolve_reads(self) -> None:
        still = []
        for (key, g, target, handle) in self._pending_reads:
            if self._applied[g] >= target:
                self.lin.end_read(handle, self._gkv[g].get(key, ""))
            else:
                still.append((key, g, target, handle))
        self._pending_reads = still

    # -- coordinator backend (reshard/coordinator.py protocol) ----------

    def journal(self, group: int, rec: dict, want: bool = True) -> None:
        from raftsql_tpu.reshard.journal import encode_record
        if want:
            self._jwant[(int(rec["id"]), rec["step"])] = int(group)
        self.node.propose_many(int(group),
                               [encode_record(rec).encode()])

    def journal_applied(self, vid: int, step: str) -> bool:
        g = self._jwant.get((int(vid), step))
        return g is not None and (int(vid), step, g) in self._jseen

    def drained(self, group: int, slots) -> bool:
        # The begin fence is already applied (j:begin gated on it), and
        # apply order == log order, so every pre-fence write for the
        # moving slots is in _gkv[group] right now; later ones bounce.
        return True

    def rows_of(self, group: int, slots) -> Dict[str, str]:
        from raftsql_tpu.reshard.keymap import slot_of
        ss = set(int(s) for s in slots)
        return {k: v for k, v in sorted(self._gkv[int(group)].items())
                if slot_of(k, self.plan.nslots) in ss}

    def copy(self, dst: int, rows: Dict[str, str]) -> None:
        vid = self.coord._cur["id"]
        payloads = [f"CPY {vid} {k} {v}".encode()
                    for k, v in sorted(rows.items())]
        if payloads:
            self.node.propose_many(int(dst), payloads)

    def copy_settled(self, dst: int, rows: Dict[str, str]) -> bool:
        kv = self._gkv[int(dst)]
        return all(kv.get(k) == v for k, v in rows.items())

    def rdel(self, group: int, slots, vid: int) -> None:
        from raftsql_tpu.reshard.journal import encode_rdel
        self.node.propose_many(
            int(group),
            [encode_rdel(slots, self.plan.nslots, vid).encode()])

    def rdel_settled(self, group: int, slots, vid: int) -> bool:
        from raftsql_tpu.reshard.keymap import slot_of
        ss = set(int(s) for s in slots)
        return not any(slot_of(k, self.plan.nslots) in ss
                       for k in self._gkv[int(group)])

    def publish(self, keymap) -> None:
        self.report["keymap_epoch"] = keymap.epoch

    def ship(self, group: int, target: int) -> None:
        d = os.path.join(self.data_dir, "reshard-ship")
        os.makedirs(d, exist_ok=True)
        blob = json.dumps(sorted(self._gkv[int(group)].items()),
                          separators=(",", ":")).encode()
        path = os.path.join(d, f"g{group}-p{target}.img")
        with open(path, "wb") as f:
            fsio.write(f, blob)
            fsio.fsync_file(f)

    def cutover(self, group: int, target: int,
                retry: bool = False) -> Optional[str]:
        from raftsql_tpu.runtime.node import TransferRefused
        group, target = int(group), int(target)
        if not self._cutover_started or retry:
            if self.node.leader_of(group) == target:
                self._cutover_started = False
                return "completed"
            try:
                self.node.transfer_leadership(group, target,
                                              deadline_ticks=40)
                self._cutover_started = True
            except TransferRefused:
                return None
        events = self.node._xfer_events
        for i in range(self._xfer_cursor, len(events)):
            if events[i]["group"] == group:
                self._xfer_cursor = i + 1
                self._cutover_started = False
                return "completed" \
                    if events[i]["outcome"] == "completed" else "aborted"
        return None

    # -- verb driving ---------------------------------------------------

    def _resolve_reshard(self, ev) -> Optional[tuple]:
        """(verb, src, dst, slots) for a plan event, or None to retry
        later.  Deterministic: resolved from seed-determined state."""
        km = self._km
        sizes = {g: len(km.slots_of(g)) for g in range(self.cfg.num_groups)}
        live = [g for g, n in sizes.items() if n > 0]
        if not live:
            return None
        if ev.verb == "split":
            src = ev.src if ev.src >= 0 else \
                max(live, key=lambda g: (sizes[g], -g))
            if sizes[src] <= 1:
                return None              # nothing to split
            if ev.dst >= 0:
                dst = ev.dst
            elif km.retired:
                dst = min(km.retired)
            else:
                others = [g for g in range(self.cfg.num_groups)
                          if g != src]
                dst = min(others, key=lambda g: (sizes[g], g))
            # Acked-key-bearing slots first: the verb should always
            # have data to prove itself on.
            owned = sorted(km.slots_of(src))
            from raftsql_tpu.reshard.keymap import slot_of
            hot = set(slot_of(k, km.nslots) for k in self.lost.acked)
            ranked = sorted(owned,
                            key=lambda s: (0 if s in hot else 1, s))
            slots = sorted(ranked[:min(ev.move_slots,
                                       max(1, sizes[src] - 1))])
            return ("split", src, dst, slots)
        if ev.verb == "merge":
            if len(live) < 2:
                return None
            src = ev.src if ev.src >= 0 else \
                min(live, key=lambda g: (sizes[g], g))
            dst = ev.dst if ev.dst >= 0 else \
                max((g for g in live if g != src),
                    key=lambda g: (sizes[g], -g))
            if src == dst:
                return None
            return ("merge", src, dst, None)
        # migrate: dst is a peer
        src = ev.src if ev.src >= 0 else min(live)
        if ev.dst >= 0:
            dst = ev.dst
        else:
            lead = self.node.leader_of(src)
            if lead < 0:
                return None
            dst = (lead + 1) % self.cfg.num_peers
        return ("migrate", src, dst, None)

    def _quiet(self, t0: int, t1: int) -> bool:
        """No scheduled fault overlaps [t0, t1) — clean air for an
        availability probe."""
        if t1 >= self.sched.ticks:
            return False
        for w in (self.sched.drops + self.sched.delays
                  + self.sched.partitions + self.sched.asym_partitions
                  + self.sched.skews):
            if w.start < t1 and t0 < w.end:
                return False
        return all(not t0 <= ev.tick < t1 for ev in self.sched.crashes)

    def _apply_faults(self, t: int, rng: np.random.Generator) -> None:
        self._tick_now = t
        # LEADER_TARGET partitions anchor on plan.part_group's leader
        # (the directed falsification plan aims them at the split's
        # DESTINATION group to starve the copy path).
        for wi, w in enumerate(self.sched.partitions):
            if w.start <= t < w.end and w.peer < 0 \
                    and wi not in self._part_peer:
                self._part_peer[wi] = max(
                    self.node.leader_of(self.plan.part_group), 0)
                self.report["partitions"] += 1
        super()._apply_faults(t, rng)
        self._drive_reshard(t)

    def _presplit(self, t: int) -> None:
        """Falsification warmup: make sure the split's dst group is not
        led by the src group's leader, so the directed partition stalls
        ONLY the copy path."""
        from raftsql_tpu.runtime.node import TransferRefused
        ev = self.plan.reshards[0]
        ls = self.node.leader_of(ev.src)
        ld = self.node.leader_of(ev.dst)
        if ls < 0 or ld < 0:
            return
        if ls != ld:
            self._presplit_done = True
            return
        try:
            self.node.transfer_leadership(
                ev.dst, (ld + 1) % self.cfg.num_peers,
                deadline_ticks=40)
        except TransferRefused:
            pass

    def _drive_reshard(self, t: int) -> None:
        # Coordinator SIGKILL / delayed rebuild.
        if t in self._kills and self.coord is not None:
            self.coord = None
            self._coord_down_until = t + self.plan.coordinator_down_ticks
            self.report["coordinator_kills"] += 1
        if self.coord is None:
            if t >= self._coord_down_until:
                self._rebuild_coordinator()
            else:
                return
        if not self._presplit_done and t >= 20:
            self._presplit(t)
        # Issue due plan verbs (retried while the coordinator is busy).
        from raftsql_tpu.reshard import ReshardRefused
        keep = []
        for ev in self._reshard_todo:
            if ev.tick > t or self.coord.busy:
                keep.append(ev)
                continue
            resolved = self._resolve_reshard(ev)
            if resolved is None:
                keep.append(ev)
                continue
            verb, src, dst, slots = resolved
            try:
                self.coord.enqueue(verb, src, dst, slots)
            except ReshardRefused:
                keep.append(ev)
        self._reshard_todo = keep
        # Orphan adoption: a begin record can apply AFTER the
        # coordinator that proposed it was killed and rebuilt (the
        # rebuild folded a journal that did not contain it yet).  An
        # idle coordinator re-folds and adopts the orphan verb.
        if not self.coord.busy and self._jrecs:
            from raftsql_tpu.reshard.journal import fold_records
            _, active = fold_records(self._jrecs, self.cfg.num_groups,
                                     self.plan.nslots)
            if active is not None:
                self.coord.recover(self._jrecs)
        self.coord.step()
        for ev in self.coord.drain_events():
            kind = ev["kind"]
            if kind == "begin":
                self.avail.verb_started(t, ev["id"])
            elif kind == "resume":
                self.report["reshard_resumed"] += 1
                self.avail.verb_started(t, ev["id"])
            elif kind == "fork-fault":
                self.report["fork_faults"] += 1
            elif kind == "flip":
                self.report["reshard_flips"] += 1
                moved = [f"k{k}" for k in range(self.KEYS)]
                from raftsql_tpu.reshard.keymap import slot_of
                moved = [k for k in moved
                         if slot_of(k, self.plan.nslots) in
                         set(ev["slots"])]
                self.lost.check_moved(
                    moved, ev["dst"], self._gkv[ev["dst"]],
                    context=f" (verb {ev['id']} {ev['verb']} "
                            f"{ev['src']}->{ev['dst']} at tick {t})")
                self.report["moved_checks"] = self.lost.moved_checks
                # Clients fail closed on the epoch bump: reads pinned
                # to the OLD owner of the moved slots are aborted, not
                # served from a shard about to be range-deleted.
                ss = set(ev["slots"])
                self._pending_reads = [
                    (key, g, target, h)
                    for (key, g, target, h) in self._pending_reads
                    if not (g == ev["src"] and
                            slot_of(key, self.plan.nslots) in ss)]
            elif kind == "done":
                self.avail.verb_resolved()
                key = {"split": "reshard_splits",
                       "merge": "reshard_merges",
                       "migrate": "reshard_migrations"}[ev["verb"]]
                self.report[key] += 1
                if not self._km.frozen:
                    self.lost.check_exclusive(
                        self._km, self._gkv,
                        context=f" (verb {ev['id']} {ev['verb']} done "
                                f"at tick {t})")
                    self.report["exclusive_checks"] = \
                        self.lost.exclusive_checks
            elif kind == "abort":
                self.avail.verb_resolved()
                self.report["reshard_aborted"] += 1
        # Availability probes: writes OUTSIDE the moving range, armed
        # in clean air while a verb is in flight.
        if self.coord is not None and self.coord.busy \
                and t % self.plan.probe_every == 0 \
                and self._quiet(t, t + self.plan.probe_ticks + 1):
            from raftsql_tpu.reshard.keymap import slot_of
            for k in range(self.KEYS):
                key = f"k{k}"
                if not self._km.is_frozen(key):
                    g = self._km.group_of(key)
                    value = f"v{self._wseq}"
                    self._wseq += 1
                    self.lin.begin_write(key, value)
                    self.node.propose_many(
                        g, [f"SET {key} {value}".encode()])
                    self.avail.arm_probe(t, key, value)
                    self.report["reshard_probes"] += 1
                    break

    # -- invariant cadence ----------------------------------------------

    def _observe(self, t: int) -> None:
        super()._observe(t)
        self.avail.check(t)
        self.report["reshard_probes_confirmed"] = \
            self.avail.probes_confirmed
        if t and t % self.EXCLUSIVE_EVERY == 0 \
                and self.coord is not None and not self.coord.busy \
                and not self._km.frozen:
            self.lost.check_exclusive(
                self._km, self._gkv,
                context=f" (steady state at tick {t})")
            self.report["exclusive_checks"] = self.lost.exclusive_checks
        if t == self.sched.ticks - 1:
            self.avail.final_check(t)

    def _report(self) -> dict:
        r = super()._report()
        r["plan_digest"] = self.plan.digest()
        r["keymap"] = self._km.to_doc()
        return r


class OverloadChaosRunner(FusedChaosRunner):
    """Overload nemesis (raftsql_tpu/overload/): an OPEN-LOOP producer
    offers `offered_per_tick` writes every tick — roughly twice what
    the engine drains — plus burst windows, hot-group skew, a fraction
    of writes carrying device-step deadlines, slow-fsync stalls and a
    mid-overload crash+restart.  The bounded admission controller is
    attached to the engine exactly the way the server does it
    (node.overload), so the nemesis exercises the REAL hot path:
    admit() under _prop_lock, stage-shed of expired deadlines before
    any WAL cost, drained() accounting, and the tick-fed drain EWMA.

    Invariants on top of the standing suite (durability ledger +
    restart replay, election safety, commit monotonicity, log
    matching, linearizable reads):

      OVERLOAD-MEMORY — the engine's ACTUAL propose backlog (every
      queue of every peer, measured under _prop_lock each tick) never
      exceeds the plan's hard cap.  This is the falsification seam:
      with `unsafe_no_admission` the controller is NOT attached, the
      producer outruns the drain, and this invariant MUST fire on the
      identical schedule the bounded control survives.

    Goodput and starvation floors are checked by chaos/run.py from
    the report (committed totals are facts of the digested history,
    not per-tick invariants)."""

    def __init__(self, plan, data_dir: str):
        self.plan = plan
        sched = ChaosSchedule(
            seed=plan.seed, ticks=plan.ticks,
            crashes=tuple(plan.crashes),
            fsync_stalls=tuple(plan.fsync_stalls),
            prop_rate=0.0, read_rate=0.0)   # workload is the open loop
        cfg = RaftConfig(num_groups=plan.groups, num_peers=plan.peers,
                         log_window=64, max_entries_per_msg=4,
                         election_ticks=10, heartbeat_ticks=1,
                         tick_interval_s=0.0)
        super().__init__(sched, data_dir, cfg=cfg)
        self._t = -1
        self._ov_totals: Dict[str, int] = {
            "admitted": 0, "rejected": 0, "shed_edge": 0,
            "shed_ring": 0, "shed_stage": 0, "shed_commit_wait": 0,
            "brownouts": 0, "queue_depth_peak": 0}
        self.report.update({
            "offered": 0, "overload_admitted": 0,
            "overload_rejected": 0, "overload_shed_stage": 0,
            "overload_brownouts": 0, "overload_depth_peak": 0})

    # -- controller attachment (the server's wiring, replayed) ---------

    def _make_node(self) -> FusedClusterNode:
        from raftsql_tpu.overload import OverloadController
        node = FusedClusterNode(self.cfg, self.data_dir,
                                seed=self.sched.seed)
        if not self.plan.unsafe_no_admission:
            node.overload = OverloadController(
                self.cfg.num_groups,
                group_cap=self.plan.group_cap,
                total_cap=self.plan.total_cap,
                seed=self.plan.seed,
                tick_interval_s=0.001)
        return node

    def _harvest(self) -> None:
        """Fold the dying (or finished) node's controller counters
        into the run totals — the controller is re-attached fresh at
        every restart, exactly as a restarted server would."""
        node = self.node
        ov = getattr(node, "overload", None) if node is not None else None
        if ov is None:
            return
        doc = ov.metrics_doc()
        for k in ("admitted", "rejected", "shed_edge", "shed_ring",
                  "shed_stage", "shed_commit_wait", "brownouts"):
            self._ov_totals[k] += int(doc[k])
        self._ov_totals["queue_depth_peak"] = max(
            self._ov_totals["queue_depth_peak"],
            int(doc["queue_depth_peak"]))

    def _crash_restart(self, tick: int, power_loss: bool = False,
                       tear_peer: int = -1) -> None:
        self._harvest()
        super()._crash_restart(tick, power_loss, tear_peer)

    # -- the open-loop workload ----------------------------------------

    def _issue(self, rng: np.random.Generator) -> None:
        from raftsql_tpu.overload import Overloaded
        self._t += 1
        t = self._t
        plan = self.plan
        node = self.node
        G = self.cfg.num_groups
        offered = plan.offered_per_tick
        for b in plan.bursts:
            if b.start <= t < b.end:
                offered += b.extra
        keys_per_group = max(1, self.KEYS // G)
        now_step = int(node._device_steps)
        for _ in range(offered):
            if rng.random() < plan.hot_share:
                g = plan.hot_group % G
            else:
                g = int(rng.integers(0, G))
            k = g + G * int(rng.integers(0, keys_per_group))
            dstep = None
            if rng.random() < plan.deadline_rate:
                dstep = now_step + int(rng.integers(plan.deadline_lo,
                                                    plan.deadline_hi + 1))
            value = f"v{self._wseq}"
            self._wseq += 1
            self.report["offered"] += 1
            try:
                node.propose_many(g, [f"SET k{k} {value}".encode()],
                                  deadline_step=dstep)
            except Overloaded:
                continue              # open loop: the producer moves on
            # Only ADMITTED writes enter the linearizability register:
            # a refused write was never acked and may never apply (a
            # deadline-shed admitted write is a begun-but-unacked
            # write, which the register models as forever-concurrent).
            self.lin.begin_write(f"k{k}", value)
        if rng.random() < plan.read_rate:
            k = int(rng.integers(0, self.KEYS))
            g = k % G
            got = node.read_index(g)
            if got:
                target, _ = got
                self._pending_reads.append(
                    (f"k{k}", g, target, self.lin.begin_read(f"k{k}")))

    # -- invariants ----------------------------------------------------

    def _observe(self, t: int) -> None:
        super()._observe(t)
        node = self.node
        with node._prop_lock:
            depth = sum(len(q) for row in node._props for q in row)
        if depth > self.report["overload_depth_peak"]:
            self.report["overload_depth_peak"] = depth
        if depth > self.plan.total_cap:
            raise InvariantViolation(
                f"OVERLOAD-MEMORY: tick {t}: propose backlog {depth} "
                f"exceeds the hard cap {self.plan.total_cap} "
                f"(admission "
                f"{'OFF' if self.plan.unsafe_no_admission else 'on'}, "
                f"offered so far {self.report['offered']})")

    def _report(self) -> dict:
        self._harvest()
        self.report["overload_admitted"] = self._ov_totals["admitted"]
        self.report["overload_rejected"] = self._ov_totals["rejected"]
        self.report["overload_shed_stage"] = \
            self._ov_totals["shed_stage"]
        self.report["overload_brownouts"] = self._ov_totals["brownouts"]
        r = super()._report()
        r["plan_digest"] = self.plan.digest()
        per = [0] * self.cfg.num_groups
        for (g, _i) in self.ledger._committed:
            per[g] += 1
        r["group_commits"] = per
        return r
