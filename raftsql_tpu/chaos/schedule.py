"""Seeded, deterministic, tick-indexed fault schedules.

A schedule is a frozen dataclass: every fault the scenario will inject
— message-drop windows, delay windows, peer partitions, crash/restart
events, storage fsync faults — pinned to tick indexes before the run
starts.  `generate(seed)` derives one from a single integer seed via
`numpy.random.default_rng`, so any failure reproduces from its seed
alone; `digest()` hashes the canonical form so `make chaos` can prove
two runs of one seed saw the identical schedule.

"Paxos vs Raft" (arXiv:2004.05074) argues raft's safety claims only
mean something under adversarial schedules of partitions and crashes;
this module is where those schedules come from.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Tuple

import numpy as np

# Partition / crash target sentinel: resolved at the window's first tick
# to whichever peer then leads group 0 — the leader-targeted kill.
LEADER_TARGET = -2


@dataclasses.dataclass(frozen=True)
class DropWindow:
    """Drop each message slot independently with probability p while
    start <= tick < end (transport.faults.random_drop)."""
    start: int
    end: int
    p: float


@dataclasses.dataclass(frozen=True)
class DelayWindow:
    """Hold each message slot with probability p for `latency` ticks
    before delivery (transport.faults.hold_messages/release_messages).
    Messages still in flight at a crash are lost — as on a real wire."""
    start: int
    end: int
    p: float
    latency: int


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """Isolate one peer (nothing in, nothing out) for the window.
    peer == LEADER_TARGET resolves to group 0's leader at `start`."""
    start: int
    end: int
    peer: int


@dataclasses.dataclass(frozen=True)
class AsymPartitionWindow:
    """ONE-directional partition for the window: `dst` stops hearing
    `src` while `src` still hears `dst` (transport.faults.asym_partition
    on the device plane; FaultPlan.block / TCP SendFaults.block on the
    wire planes).  src == LEADER_TARGET resolves to group 0's leader at
    `start` — "the cluster goes deaf to its leader" is the classic
    half-open failure."""
    start: int
    end: int
    src: int
    dst: int


@dataclasses.dataclass(frozen=True)
class SkewWindow:
    """Per-peer clock skew: while start <= tick < end, peer p's
    election/heartbeat timers advance incs[p] intervals per tick
    (1 = nominal).  Integer rates express relative drift — a peer at 2
    experiences time twice as fast as its cluster; real deployments
    never tick in lockstep, and the batched runtime's lockstep default
    is exactly the assumption this window breaks."""
    start: int
    end: int
    incs: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class EnospcFault:
    """Peer `peer`'s op-th WAL write ATTEMPT fails with ENOSPC before
    any byte lands (storage/fsio.py check_write).  The runner treats it
    as fatal — crash + restart — and the consumed trigger models the
    operator freeing space, so the retry succeeds from a clean tail."""
    peer: int
    op: int


@dataclasses.dataclass(frozen=True)
class FsyncStall:
    """Peer `peer`'s fsyncs op .. op+count-1 stall `stall_s` seconds
    each (slow disk, not failed disk): durability holds, latency
    suffers, every invariant must survive the slowdown."""
    peer: int
    op: int
    count: int = 3
    stall_s: float = 0.02


@dataclasses.dataclass(frozen=True)
class CorruptWindow:
    """Wire-frame corruption (loopback / TCP planes): while active,
    each encoded frame is bit-flipped with probability p.  The CRC32
    framing (transport/codec.py) must catch and drop every mangled
    frame — corruption may cost progress, never correctness."""
    start: int
    end: int
    p: float


@dataclasses.dataclass(frozen=True)
class CrashEvent:
    """Hard process crash at `tick` (the whole fused cluster process),
    followed by immediate restart-from-WAL.  power_loss=True models a
    machine crash instead: everything not fsynced is dropped, and
    `tear_peer` (if >= 0) additionally has its last WAL write torn
    mid-record.  Scheduled crashes fire on tick boundaries (post-
    barrier); MID-tick power loss comes from TornWriteFault."""
    tick: int
    power_loss: bool = False
    tear_peer: int = -1


@dataclasses.dataclass(frozen=True)
class FsyncFault:
    """The op-th fsync under peer `peer`'s WAL directory raises (a
    failed disk flush).  The runner treats it as fatal for the process
    — crash + restart — which is the etcd posture (panic on WAL sync
    failure rather than ack unsynced data)."""
    peer: int
    op: int


@dataclasses.dataclass(frozen=True)
class TornWriteFault:
    """Power loss mid-way through peer `peer`'s op-th WAL record write:
    the machine dies with the record partially in the page cache and
    nothing of the current tick fsynced.  The runner tears that record
    (truncates it mid-write), drops every other file's unsynced tail,
    and restarts — WAL._repair_tail and epoch repair must recover."""
    peer: int
    op: int


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A complete scripted scenario for the fused runtime."""
    seed: int
    ticks: int
    drops: Tuple[DropWindow, ...] = ()
    delays: Tuple[DelayWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    fsync_faults: Tuple[FsyncFault, ...] = ()
    torn_writes: Tuple[TornWriteFault, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    skews: Tuple[SkewWindow, ...] = ()
    enospc_faults: Tuple[EnospcFault, ...] = ()
    fsync_stalls: Tuple[FsyncStall, ...] = ()
    # Aggressive-compaction interleaving: every `compact_every` ticks the
    # runner advances every peer's compaction floor to applied -
    # compact_keep (clamped to the device window) — so crashes and
    # restarts land on compacted WALs (COMPACT markers, segment drops,
    # floor-aware replay).  0 = never compact (the pre-matrix default).
    compact_every: int = 0
    compact_keep: int = 0
    prop_rate: float = 0.5       # P(issue a PUT batch) per tick
    read_rate: float = 0.35      # P(issue a linearizable GET) per tick

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class NodeCrash:
    """Threaded-cluster plane: kill peer `peer` (0-based, or
    LEADER_TARGET) at `tick`, restart it `down` ticks later."""
    tick: int
    peer: int
    down: int = 30


@dataclasses.dataclass(frozen=True)
class NodeChaosPlan:
    """Scripted scenario for the lockstep RaftNode cluster."""
    seed: int
    ticks: int
    partitions: Tuple[PartitionWindow, ...] = ()
    crashes: Tuple[NodeCrash, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    skews: Tuple[SkewWindow, ...] = ()
    corruptions: Tuple[CorruptWindow, ...] = ()
    # Snapshot-interleaving knobs (SnapshotChaosRunner): aggressive
    # per-node compaction cadence, retained window, and a fault-free
    # heal window at the end of the run over which survivors must
    # CONVERGE (the post-snapshot convergence invariant).
    compact_every: int = 0
    compact_keep: int = 0
    heal_ticks: int = 0
    prop_rate: float = 0.4

    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class TcpChaosPlan:
    """Scripted scenario for a RaftNode cluster over the REAL TCP
    transport (transport/tcp.py + its SendFaults seam).  Frames cross
    actual localhost sockets, so arrival interleaving is kernel-
    scheduled: the SCHEDULE is deterministic from the seed, the
    invariants must hold on every run, but the committed history is not
    bit-reproducible (documented in the README fault matrix — this is
    the one plane where a virtual clock does not exist)."""
    seed: int
    ticks: int
    drops: Tuple[DropWindow, ...] = ()
    corruptions: Tuple[CorruptWindow, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    delays: Tuple[DelayWindow, ...] = ()       # latency in ms units
    heal_ticks: int = 60
    prop_rate: float = 0.5

    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class MemberEvent:
    """Admin membership op issued at `tick` against every group's
    leader (retried each tick until the leader accepts it):
    op in {add_learner, promote, remove, remove_learner}."""
    tick: int
    op: str
    peer: int


@dataclasses.dataclass(frozen=True)
class NodeBoot:
    """Boot peer slot `peer` (fresh, empty WAL — "a new machine") at
    `tick`; before that the slot is provisioned capacity, down."""
    tick: int
    peer: int


@dataclasses.dataclass(frozen=True)
class MembershipChaosPlan:
    """Scripted membership churn for the lockstep RaftNode cluster:
    node replacement under faults.  `initial_voters` seeds the boot
    config over `peers` provisioned slots; `initial_down` slots start
    unbooted (spare machines)."""
    seed: int
    ticks: int
    peers: int
    initial_voters: Tuple[int, ...]
    initial_down: Tuple[int, ...] = ()
    boots: Tuple[NodeBoot, ...] = ()
    events: Tuple[MemberEvent, ...] = ()
    crashes: Tuple[NodeCrash, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    drops: Tuple[DropWindow, ...] = ()
    # Base-runner parity (NodeClusterChaosRunner drives this plan too):
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    skews: Tuple[SkewWindow, ...] = ()
    corruptions: Tuple[CorruptWindow, ...] = ()
    heal_ticks: int = 60
    prop_rate: float = 0.5
    # Expected stable config after the script (checked post-heal).
    final_voters: Tuple[int, ...] = ()

    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class TcpRebindPlan:
    """TCP-plane crash/restart with PORT REBINDING: stop a node (its
    listener closes), restart it `down` ticks later on the SAME port
    and data dir — peers' senders must reconnect and the restarted
    node must catch up.  Same reproducibility posture as TcpChaosPlan
    (deterministic schedule, kernel-scheduled arrivals)."""
    seed: int
    ticks: int
    restarts: Tuple[NodeCrash, ...] = ()
    heal_ticks: int = 80
    prop_rate: float = 0.6

    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class ProcKill:
    """PROCESS-plane crash: SIGKILL peer `peer` (0-based, or
    LEADER_TARGET resolved via /healthz at fire time) at host tick
    `tick`; the nemesis respawns it `down` ticks later on the SAME
    ports and data dir."""
    tick: int
    peer: int
    down: int = 10


@dataclasses.dataclass(frozen=True)
class ProcStall:
    """SIGSTOP peer `peer` at `tick`, SIGCONT `ticks` ticks later — the
    GC-pause / VM-freeze failure mode.  A stalled LEADER must be
    deposed while frozen and rejoin as a follower on SIGCONT, with
    every write acked before the stall intact."""
    tick: int
    peer: int
    ticks: int = 8


@dataclasses.dataclass(frozen=True)
class ProcRestartStorm:
    """Rolling-restart storm starting at `tick`: each peer in turn gets
    a clean SIGTERM stop and an immediate respawn (same port — every
    respawn is also a same-port rebind), `gap` ticks apart — the
    deploy-day scenario."""
    tick: int
    gap: int = 4


@dataclasses.dataclass(frozen=True)
class ProcFsioSpec:
    """Env-injected storage faults for peer `peer`'s FIRST spawn: the
    RAFTSQL_FSIO_FAULTS value (storage/fsio.py grammar).  Crash-point
    specs (exit_fsync) hard-exit the child; the nemesis respawns it
    WITHOUT the spec — the fault fired, the disk "recovered"."""
    peer: int
    spec: str


@dataclasses.dataclass(frozen=True)
class ProcChaosPlan:
    """Scripted scenario for a REAL multi-process cluster
    (server/main.py children, TcpTransport, HTTP clients).  Host ticks
    are wall-clock paced (`tick_s`), so this plane has the WEAKEST
    determinism contract of the harness: the SCHEDULE is a pure
    function of the seed (digest-compared), the invariant VERDICTS
    must reproduce, but the committed history is scheduled by three
    kernels' worth of real concurrency and is not bit-reproducible
    (documented in the README fault matrix)."""
    seed: int
    ticks: int
    peers: int = 3
    kills: Tuple[ProcKill, ...] = ()
    stalls: Tuple[ProcStall, ...] = ()
    storms: Tuple[ProcRestartStorm, ...] = ()
    fsio: Tuple[ProcFsioSpec, ...] = ()
    heal_ticks: int = 40
    tick_s: float = 0.25
    groups: int = 1

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class ReadNemesisPlan:
    """Scripted read-plane attack (fused plane, chaos/scenarios.py
    ReadNemesisRunner): writes race lease / ReadIndex / session /
    follower reads while clock skew, partitions, leader kills and
    crashes land — checked by the real-time read-linearizability and
    session-consistency invariants.

    A SEPARATE plan class on purpose: extending ChaosSchedule would
    change the asdict() digest of every existing family.  The runner
    projects the fault fields into a ChaosSchedule internally so fault
    application shares the proven code paths.

    `lease_ticks`/`max_clock_skew` configure the engine's lease bound;
    `max_skew_rate` caps the per-peer timer rates the skew windows
    draw.  The SAFE sizing contract (config.py lease_ticks) is
    lease_ticks + max_clock_skew <= election_ticks / max_skew_rate;
    `broken_lease=True` deliberately violates it (the falsification
    plan — the invariant must then CATCH a stale lease read)."""
    seed: int
    ticks: int
    peers: int = 3
    groups: int = 4
    election_ticks: int = 16
    lease_ticks: int = 6
    max_clock_skew: int = 1
    max_skew_rate: int = 2
    broken_lease: bool = False
    skews: Tuple[SkewWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    prop_rate: float = 0.8
    lease_read_rate: float = 0.8
    read_index_rate: float = 0.5
    session_read_rate: float = 0.5
    follower_read_rate: float = 0.5

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


# TransferEvent.target sentinel: resolved at issue time to the peer the
# plan's FIRST partition window isolated (the "lagging" peer — behind by
# a whole window of appends).  The directed falsification plan uses it
# to aim a transfer at a provably-behind target.
XFER_LAGGER = -3


@dataclasses.dataclass(frozen=True)
class TransferEvent:
    """Graceful leadership transfer requested at `tick` (retried each
    tick until the engine accepts it or the retry budget runs out).
    `group` -1 = any group currently led by someone other than the
    resolved target; `target` -1 = the leader's successor slot
    ((leader + 1) % peers), XFER_LAGGER = the first partition window's
    isolated peer.  `must_complete` marks the directed falsification
    probe: the transfer MUST end `completed` within the plan's
    max_stall_ticks — the §3.10-broken kernel (unsafe_transfer) deposes
    the old leader before the target caught up, the behind target can
    never win the election, and the transfer ABORTS instead."""
    tick: int
    group: int = -1
    target: int = -1
    must_complete: bool = False


@dataclasses.dataclass(frozen=True)
class TransferNemesisPlan:
    """Scripted transfer-under-nemesis attack (fused plane,
    chaos/scenarios.py TransferChaosRunner): graceful leadership
    transfers race the existing nemesis arsenal — drops, partitions
    (leader-targeted kills), one-directional cuts, clock skew, and
    crash+restart — under live acked-PUT load, checked by the
    TransferAvailability invariant (bounded per-transfer proposal
    stall, aborted transfers re-open the group) on top of the standing
    election-safety / durability / linearizability invariants.

    A SEPARATE plan class on purpose (ReadNemesisPlan precedent):
    extending ChaosSchedule would change the asdict() digest of every
    existing family.  The runner projects the fault fields into a
    ChaosSchedule internally so fault application shares the proven
    code paths.

    `unsafe_transfer=True` compiles the deliberately broken transfer
    kernel (config.py unsafe_transfer: no catch-up gate, instant
    abdication) — the falsification plan the harness must CATCH."""
    seed: int
    ticks: int
    peers: int = 3
    groups: int = 4
    transfers: Tuple[TransferEvent, ...] = ()
    drops: Tuple[DropWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    skews: Tuple[SkewWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    election_ticks: int = 10
    # Engine-side per-transfer deadline (device steps): past it the host
    # clears the latch and the group resumes under the old leader.
    deadline_ticks: int = 40
    # Directed stall bound for must_complete transfers (falsification).
    max_stall_ticks: int = 60
    # A probe write proposed when a transfer resolves inside a
    # fault-free window must commit within this many ticks (the
    # "group keeps serving" leg of the availability invariant).
    probe_ticks: int = 30
    unsafe_transfer: bool = False
    prop_rate: float = 0.7
    read_rate: float = 0.25

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_transfers(seed: int, ticks: int = 260,
                       peers: int = 3) -> TransferNemesisPlan:
    """The transfer-under-nemesis family: graceful transfers land
    before, inside, and after each fault window — a leader-targeted
    partition (the fused plane's leader kill), a one-directional cut, a
    drop window, a clock-skew window, and a whole-process crash — while
    the acked-PUT workload keeps running.  At least two transfers fall
    in fault-free air so their serving probes actually assert."""
    rng = np.random.default_rng(seed ^ 0x7AFE)
    warmup = 40
    groups = 4
    # Fault windows in the middle third-ish of the run.
    p0 = int(rng.integers(warmup + 20, ticks // 3))
    part = PartitionWindow(p0, p0 + int(rng.integers(20, 30)),
                           LEADER_TARGET)
    a0 = int(rng.integers(ticks // 3 + 10, ticks // 2))
    asym = AsymPartitionWindow(a0, a0 + int(rng.integers(15, 25)),
                               LEADER_TARGET, int(rng.integers(0, peers)))
    d0 = int(rng.integers(ticks // 2, int(ticks * 0.62)))
    drop = DropWindow(d0, d0 + int(rng.integers(15, 25)),
                      float(rng.uniform(0.08, 0.2)))
    incs = [1] * peers
    incs[int(rng.integers(0, peers))] = 2
    s0 = int(rng.integers(int(ticks * 0.62), int(ticks * 0.72)))
    skew = SkewWindow(s0, s0 + int(rng.integers(12, 20)), tuple(incs))
    crash = CrashEvent(int(rng.integers(int(ticks * 0.72),
                                        int(ticks * 0.8))))
    # Transfers: two in the clean warmup air, one inside each fault
    # window (racing it), two in the post-crash tail.
    evs = [
        TransferEvent(warmup, int(rng.integers(0, groups))),
        TransferEvent(warmup + 8, int(rng.integers(0, groups))),
        TransferEvent(part.start + 5, int(rng.integers(0, groups))),
        TransferEvent(asym.start + 4, int(rng.integers(0, groups))),
        TransferEvent(drop.start + 4, int(rng.integers(0, groups))),
        TransferEvent(skew.start + 3, int(rng.integers(0, groups))),
        TransferEvent(crash.tick + 12, int(rng.integers(0, groups))),
        TransferEvent(crash.tick + 24, int(rng.integers(0, groups))),
    ]
    return TransferNemesisPlan(
        seed=seed, ticks=max(ticks, crash.tick + 70), peers=peers,
        groups=groups, transfers=tuple(evs), drops=(drop,),
        partitions=(part,), asym_partitions=(asym,), skews=(skew,),
        crashes=(crash,))


def falsification_transfer_plan(seed: int = 0,
                                broken: bool = True
                                ) -> TransferNemesisPlan:
    """DIRECTED transfer-falsification scenario: a long leader-targeted
    partition leaves one peer a full window of appends behind; after
    the heal, a must_complete transfer aims a group at exactly that
    lagging peer.  The CORRECT kernel (thesis §3.10) holds the
    TimeoutNow until the target's match_index catches up, then the
    target wins immediately — the transfer COMPLETES well inside
    max_stall_ticks.  broken=True compiles the unsafe kernel (no
    catch-up gate, instant abdication): the behind target calls an
    election it cannot win (log restriction), the group goes leaderless
    until a third peer times out, and the transfer ABORTS — the
    TransferAvailability invariant MUST fire on the same schedule,
    proving the harness detects the §3.10 mistake, not chaos in
    general."""
    # The transfer fires AT the heal tick: the lagger is still a full
    # window of appends behind (replication closes the gap at
    # max_entries_per_msg per tick, so waiting even a handful of ticks
    # would hand the broken kernel an already-caught-up target and
    # nothing to falsify).
    part = PartitionWindow(40, 100, LEADER_TARGET)
    xfer = TransferEvent(100, group=-1, target=XFER_LAGGER,
                         must_complete=True)
    return TransferNemesisPlan(
        seed=seed, ticks=200, peers=3, groups=2,
        transfers=(xfer,), partitions=(part,),
        election_ticks=10, deadline_ticks=80, max_stall_ticks=60,
        probe_ticks=40, unsafe_transfer=broken,
        prop_rate=1.0, read_rate=0.2)


def generate_reads(seed: int, ticks: int = 240,
                   peers: int = 3) -> ReadNemesisPlan:
    """The read-linearizability nemesis family: two skew windows at
    rates within the configured bound, a leader-targeted full
    partition, a one-directional leader cut, and a crash — all while
    every read mode races the write stream.  Lease bound sized SAFELY
    (election 16, rate cap 2, lease 6 + skew 1 < 16/2): under this
    schedule a lease read must NEVER be stale, and the run asserts the
    invariant checked every family."""
    rng = np.random.default_rng(seed ^ 0x4EAD)
    warmup = 40
    rate = 2

    def draw_incs() -> Tuple[int, ...]:
        incs = [1] * peers
        fast = int(rng.integers(0, peers))
        incs[fast] = rate
        if rng.random() < 0.5:
            incs[int((fast + 1) % peers)] = 0    # a stalled clock too
        return tuple(incs)

    s0 = int(rng.integers(warmup, warmup + ticks // 4))
    w0 = SkewWindow(s0, s0 + int(rng.integers(25, 40)), draw_incs())
    s1 = int(rng.integers(ticks // 2, int(ticks * 0.75)))
    w1 = SkewWindow(s1, s1 + int(rng.integers(25, 40)), draw_incs())
    p0 = int(rng.integers(warmup, ticks // 3))
    part = PartitionWindow(p0, p0 + int(rng.integers(25, 40)),
                           LEADER_TARGET)
    a0 = int(rng.integers(ticks // 3, int(ticks * 0.7)))
    asym = AsymPartitionWindow(a0, a0 + int(rng.integers(20, 35)),
                               LEADER_TARGET,
                               int(rng.integers(0, peers)))
    crash = CrashEvent(int(rng.integers(int(ticks * 0.55),
                                        int(ticks * 0.85))))
    return ReadNemesisPlan(seed=seed, ticks=ticks, peers=peers,
                           election_ticks=16, lease_ticks=6,
                           max_clock_skew=1, max_skew_rate=rate,
                           skews=(w0, w1), partitions=(part,),
                           asym_partitions=(asym,), crashes=(crash,))


def falsification_plan(seed: int = 0,
                       broken: bool = True) -> ReadNemesisPlan:
    """DIRECTED lease-falsification scenario: both followers run their
    clocks at 4x through a long leader partition, so a new leader is
    elected (election_ticks/4 of ITS clock) while the old one still
    sits inside a mis-sized lease.  broken=True sizes the lease at
    election_ticks (legal only for rate <= ~1) — the stale window is
    real and the read-linearizability invariant MUST fire.
    broken=False sizes it to the actual rate (16/4 - margin) — the
    same schedule must pass, which proves the harness is sensitive to
    exactly the bound and not just to chaos in general."""
    # All clocks at 4x (the lease is measured in device steps, so the
    # leader's own rate is irrelevant — what matters is how fast the
    # FOLLOWERS' election timers run); the partition resolves to
    # whoever leads group 0 when it opens.
    skew = SkewWindow(40, 160, (4, 4, 4))
    part = PartitionWindow(50, 160, LEADER_TARGET)
    return ReadNemesisPlan(
        seed=seed, ticks=200, peers=3, groups=2,
        election_ticks=16,
        # Broken: the lease outlives the whole election dance the 4x
        # clocks run behind the partition (sized like an operator who
        # tuned for no skew at all); correct: within election/rate.
        lease_ticks=100 if broken else 3,
        max_clock_skew=0, max_skew_rate=4,
        broken_lease=broken,
        skews=(skew,), partitions=(part,),
        prop_rate=1.0, lease_read_rate=1.0,
        read_index_rate=0.4, session_read_rate=0.4,
        follower_read_rate=0.4)


@dataclasses.dataclass(frozen=True)
class QuorumNemesisPlan:
    """Scripted quorum-geometry attack (fused plane, chaos/scenarios.py
    QuorumChaosRunner): flexible write/election quorums and witness
    peers under the read-nemesis workload — acked PUTs race lease and
    ReadIndex reads while partitions, asymmetric cuts, clock skew and
    crash+restart land.

    A SEPARATE plan class on purpose (same rule as ReadNemesisPlan):
    extending an existing plan would change the asdict() digest of
    every committed family.  The runner projects the fault fields into
    a ChaosSchedule internally and forwards the geometry fields into
    RaftConfig (write_quorum / election_quorum / witnesses /
    unsafe_quorum_geometry / unsafe_witness_lease).

    `pin_leader_tick` >= 0 pins group 0's leadership onto
    `pin_leader_peer` (transfer_leadership, retried for a few ticks)
    before the fault windows open — the directed falsification plans
    need to know WHO the partitioned leader is so the windows can be
    written against fixed peer ids instead of LEADER_TARGET."""
    seed: int
    ticks: int
    peers: int = 3
    groups: int = 2
    election_ticks: int = 16
    lease_ticks: int = 6
    max_clock_skew: int = 1
    max_skew_rate: int = 2
    write_quorum: "int | None" = None
    election_quorum: "int | None" = None
    witnesses: Tuple[int, ...] = ()
    unsafe_geometry: bool = False
    broken_witness_lease: bool = False
    pin_leader_tick: int = -1
    pin_leader_peer: int = 0
    skews: Tuple[SkewWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    prop_rate: float = 0.8
    lease_read_rate: float = 0.8
    read_index_rate: float = 0.4
    # Session/follower reads resolve at a RANDOM peer in the read
    # nemesis; a witness peer has no apply state to answer from, so the
    # quorum family keeps these modes off by default.
    session_read_rate: float = 0.0
    follower_read_rate: float = 0.0

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_quorum(seed: int, ticks: int = 240) -> QuorumNemesisPlan:
    """The quorum-geometry nemesis family: a 2-voter + 1-witness
    cluster (the last peer is the witness; W = E = 2 explicit, which
    intersects: W+E > N, 2E > N) sustains acked PUTs plus lease and
    ReadIndex reads through two skew windows within the configured
    bound, a leader-targeted full partition, a one-directional leader
    cut, and a whole-cluster crash+restart — the restart replays the
    witness's vote/term/log purely from its WAL (it has no shard).
    Geometry is CORRECT, so every standing invariant must hold and
    digests must reproduce across runs; the witness must accumulate
    replicated appends (witness_appends) while its publish stream
    stays empty (one fewer apply stream than WAL streams)."""
    rng = np.random.default_rng(seed ^ 0x9E0)
    warmup = 40
    rate = 2
    wit = 2                       # witness slot: the last of 3 peers

    def draw_incs() -> Tuple[int, ...]:
        incs = [1, 1, 1]
        fast = int(rng.integers(0, 3))
        incs[fast] = rate
        if rng.random() < 0.5:
            incs[int((fast + 1) % 3)] = 0        # a stalled clock too
        return tuple(incs)

    s0 = int(rng.integers(warmup, warmup + ticks // 4))
    w0 = SkewWindow(s0, s0 + int(rng.integers(25, 40)), draw_incs())
    s1 = int(rng.integers(ticks // 2, int(ticks * 0.75)))
    w1 = SkewWindow(s1, s1 + int(rng.integers(25, 40)), draw_incs())
    p0 = int(rng.integers(warmup, ticks // 3))
    part = PartitionWindow(p0, p0 + int(rng.integers(25, 40)),
                           LEADER_TARGET)
    a0 = int(rng.integers(ticks // 3, int(ticks * 0.7)))
    asym = AsymPartitionWindow(a0, a0 + int(rng.integers(20, 35)),
                               LEADER_TARGET,
                               int(rng.integers(0, 2)))  # a voter
    crash = CrashEvent(int(rng.integers(int(ticks * 0.55),
                                        int(ticks * 0.85))))
    return QuorumNemesisPlan(seed=seed, ticks=ticks, peers=3, groups=2,
                             election_ticks=16, lease_ticks=6,
                             max_clock_skew=1, max_skew_rate=rate,
                             write_quorum=2, election_quorum=2,
                             witnesses=(wit,),
                             skews=(w0, w1), partitions=(part,),
                             asym_partitions=(asym,), crashes=(crash,))


def falsification_quorum_plan(seed: int = 0,
                              broken: bool = True) -> QuorumNemesisPlan:
    """DIRECTED split-brain falsification for flexible quorums: pin
    group 0's leadership to peer 0, then isolate peer 0 for a long
    window.  broken=True runs W=1 / E=2 (W + E <= N — the
    non-intersecting geometry config.py refuses without
    unsafe_quorum_geometry): the isolated leader keeps solo-committing
    the acked writes still routed at it while the other two peers
    elect (E=2 holds without it) and commit DIFFERENT entries into the
    same slots — two peers surface different payloads for one
    (group, index) and the harness MUST catch it (the cross-peer
    durability view's changed-content check, log matching, or commit
    monotonicity, whichever observes first).  broken=False runs the
    SAME schedule at W=2: the isolated leader can no longer commit
    alone and the run must pass — proving the harness is sensitive to
    exactly the geometry, not to chaos in general."""
    part = PartitionWindow(60, 170, 0)       # the pinned leader
    return QuorumNemesisPlan(
        seed=seed, ticks=220, peers=3, groups=1,
        election_ticks=10,
        lease_ticks=0, max_clock_skew=0, max_skew_rate=1,
        write_quorum=1 if broken else 2, election_quorum=2,
        unsafe_geometry=broken,
        pin_leader_tick=30, pin_leader_peer=0,
        partitions=(part,),
        prop_rate=1.0, lease_read_rate=0.0, read_index_rate=0.0)


def falsification_witness_plan(seed: int = 0,
                               broken: bool = True) -> QuorumNemesisPlan:
    """DIRECTED stale-lease falsification for witness accounting: pin
    group 0's leadership to peer 1 (a full voter; peer 2 is the
    witness), isolate it at tick 70, and run candidate peer 0's clock
    at 4x so its election timer fires INSIDE the deposed leader's
    still-live lease (lease 12 from the last pre-partition quorum ack
    ~ tick 69; the 16..32-tick timeout draw lands at tick 74..78 of
    real time).  broken=True sets unsafe_witness_lease: the witness
    grants the prevote despite sitting inside the leader's lease
    window, peer 0 wins (E=2 = itself + the witness), commits new
    acked writes — and the isolated leader, lease in hand, serves a
    lease read of the OLD value.  The register invariant MUST catch it
    as a stale lease read.  broken=False runs the SAME schedule with
    the honest witness: the prevote is refused until the witness's own
    election timer clears (tick ~86, after the lease died at ~87 — the
    first honest COMMIT lands later still), so the run must pass —
    proving a witness counted toward the LEASE quorum is exactly the
    bug, not chaos in general.  Lease 18 is the directed sweet spot:
    long enough that the usurper's first committed writes (~tick 80)
    land while the deposed leader still serves (stale window ~80..84),
    short enough that the honest election cannot complete inside it."""
    skew = SkewWindow(60, 130, (4, 1, 1))    # candidate clock at 4x
    part = PartitionWindow(70, 130, 1)       # isolate the pinned leader
    return QuorumNemesisPlan(
        seed=seed, ticks=170, peers=3, groups=1,
        election_ticks=16, lease_ticks=18,
        max_clock_skew=0, max_skew_rate=4,
        witnesses=(2,), write_quorum=2, election_quorum=2,
        broken_witness_lease=broken,
        pin_leader_tick=25, pin_leader_peer=1,
        skews=(skew,), partitions=(part,),
        prop_rate=1.0, lease_read_rate=1.0, read_index_rate=0.0)


def generate_procs(seed: int, ticks: int = 80,
                   peers: int = 3) -> ProcChaosPlan:
    """Derive a process-plane scenario from one seed, with every fault
    family the acceptance gate names aboard: a leader-targeted SIGKILL,
    a random SIGKILL, a leader SIGSTOP/SIGCONT stall, one rolling
    restart storm, an env-injected ENOSPC on one peer's WAL and an
    exit-at-fsync crash point on another.  Low fsio op counts fire the
    storage faults within the warmup writes, before the first scripted
    signal lands."""
    rng = np.random.default_rng(seed ^ 0x90C)
    warmup = max(10, ticks // 8)
    t_kill0 = int(rng.integers(warmup, warmup + ticks // 4))
    kill0 = ProcKill(t_kill0, LEADER_TARGET,
                     down=int(rng.integers(8, 13)))
    t_stall = int(rng.integers(t_kill0 + kill0.down + 4,
                               t_kill0 + kill0.down + 4 + ticks // 4))
    stall = ProcStall(t_stall, LEADER_TARGET,
                      ticks=int(rng.integers(6, 10)))
    t_kill1 = int(rng.integers(t_stall + stall.ticks + 4,
                               t_stall + stall.ticks + 4 + ticks // 6))
    kill1 = ProcKill(t_kill1, int(rng.integers(0, peers)),
                     down=int(rng.integers(6, 11)))
    t_storm = t_kill1 + kill1.down + int(rng.integers(4, 9))
    storm = ProcRestartStorm(t_storm, gap=int(rng.integers(3, 6)))
    # Two peers get env-injected disk faults; WAL write/fsync op counts
    # accumulate with the warmup workload, so low-20s thresholds fire
    # in the first seconds of serving.
    p_enospc = int(rng.integers(0, peers))
    p_exit = int((p_enospc + 1 + rng.integers(0, peers - 1)) % peers)
    fsio = (
        ProcFsioSpec(p_enospc,
                     f"raftsql-{p_enospc + 1}:"
                     f"enospc@{int(rng.integers(12, 25))}"),
        ProcFsioSpec(p_exit,
                     f"raftsql-{p_exit + 1}:"
                     f"exit_fsync@{int(rng.integers(10, 20))}"),
    )
    total = max(ticks, t_storm + storm.gap * peers + 8)
    return ProcChaosPlan(seed=seed, ticks=total, peers=peers,
                         kills=(kill0, kill1), stalls=(stall,),
                         storms=(storm,), fsio=fsio)


def generate(seed: int, ticks: int = 240, peers: int = 3,
             min_partitions: int = 2, min_crashes: int = 2,
             min_fsync_faults: int = 1,
             min_torn_writes: int = 1,
             with_delays: bool = True) -> ChaosSchedule:
    """Derive a full scenario from one seed.

    Guarantees the floors the acceptance gate needs: >= min_partitions
    partition windows (at least one leader-targeted), >= min_crashes
    crash/restart events, >= min_fsync_faults injected fsync failures,
    and >= min_torn_writes mid-write power losses (each also a
    crash/restart).
    """
    rng = np.random.default_rng(seed)
    warmup = 40                          # let first elections settle

    n_part = int(min_partitions + rng.integers(0, 2))
    parts = []
    for i in range(n_part):
        start = int(rng.integers(warmup, max(warmup + 1,
                                             ticks - 60)))
        length = int(rng.integers(20, 41))
        # First window is always the leader-targeted kill.
        peer = LEADER_TARGET if i == 0 else int(rng.integers(0, peers))
        parts.append(PartitionWindow(start, min(start + length, ticks),
                                     peer))
    parts.sort(key=lambda w: w.start)

    n_crash = int(min_crashes + rng.integers(0, 2))
    lo, hi = int(ticks * 0.35), int(ticks * 0.9)
    crash_ticks = sorted(int(t) for t in rng.choice(
        np.arange(lo, hi), size=n_crash, replace=False))
    # Scheduled crashes land on tick boundaries, where the durable
    # barrier has just completed — they exercise clean process-kill
    # replay.  Power-loss recovery (unsynced/torn tails) is exercised
    # by the torn-write faults below, which fire MID-tick.
    crashes = tuple(CrashEvent(t) for t in crash_ticks)

    # Each active tick fsyncs every peer once, so op counts in the low
    # tens always fire well before the first crash window.
    faults = tuple(FsyncFault(int(rng.integers(0, peers)),
                              int(rng.integers(15, 30)) + 10 * i)
                   for i in range(min_fsync_faults))
    # Every active tick writes at least a hard-state record per peer;
    # write ops accumulate a few per active tick, so these fire mid-run.
    torn = tuple(TornWriteFault(int(rng.integers(0, peers)),
                                int(rng.integers(60, 120)) + 40 * i)
                 for i in range(min_torn_writes))

    drops = (DropWindow(int(rng.integers(warmup, ticks // 2)),
                        int(rng.integers(ticks // 2, ticks)),
                        float(rng.uniform(0.05, 0.2))),)
    delays = ()
    if with_delays:
        d0 = int(rng.integers(warmup, ticks - 40))
        delays = (DelayWindow(d0, d0 + int(rng.integers(20, 40)),
                              float(rng.uniform(0.1, 0.3)),
                              int(rng.integers(2, 5))),)

    return ChaosSchedule(seed=seed, ticks=ticks, drops=drops,
                         delays=delays, partitions=tuple(parts),
                         crashes=crashes, fsync_faults=faults,
                         torn_writes=torn)


# ---------------------------------------------------------------------------
# Scenario FAMILY generators — one per uncovered fault-matrix axis
# (ROADMAP open items).  Each derives a focused schedule from one seed:
# the family's faults plus light background load, sized so a fast
# tier-1 run stays cheap and `make chaos-matrix` can sweep one seed per
# family.  All are deterministic functions of (seed, ticks).

def generate_asym(seed: int, ticks: int = 160,
                  peers: int = 3) -> ChaosSchedule:
    """Asymmetric partitions (fused plane): one leader-targeted deafness
    window (the cluster stops hearing its leader), one random
    one-directional link cut, plus a crash so recovery interleaves."""
    rng = np.random.default_rng(seed ^ 0xA51)
    warmup = 40
    s0 = int(rng.integers(warmup, ticks // 2))
    d0 = int(rng.integers(0, peers))
    asym = [AsymPartitionWindow(s0, s0 + int(rng.integers(25, 40)),
                                LEADER_TARGET, d0)]
    s1 = int(rng.integers(ticks // 2, ticks - 30))
    src = int(rng.integers(0, peers))
    dst = int((src + 1 + rng.integers(0, peers - 1)) % peers)
    asym.append(AsymPartitionWindow(s1, s1 + int(rng.integers(20, 35)),
                                    src, dst))
    crash = CrashEvent(int(rng.integers(int(ticks * 0.55),
                                        int(ticks * 0.85))))
    return ChaosSchedule(seed=seed, ticks=ticks,
                         asym_partitions=tuple(asym), crashes=(crash,))


def generate_skew(seed: int, ticks: int = 160, peers: int = 3,
                  max_inc: int = 3) -> ChaosSchedule:
    """Per-peer clock skew (fused plane): two windows of drifting timer
    rates — one peer fast, later another — with a crash between them.
    The lockstep run of the SAME seed minus the skews is the regression
    baseline: election outcomes must demonstrably differ."""
    rng = np.random.default_rng(seed ^ 0x5E3)
    warmup = 30

    def draw_incs() -> Tuple[int, ...]:
        incs = [1] * peers
        fast = int(rng.integers(0, peers))
        incs[fast] = int(rng.integers(2, max_inc + 1))
        slow = int((fast + 1) % peers)
        if rng.random() < 0.5:
            incs[slow] = 0               # a stalled clock, not just slow
        return tuple(incs)

    s0 = int(rng.integers(warmup, ticks // 3))
    w0 = SkewWindow(s0, s0 + int(rng.integers(30, 50)), draw_incs())
    s1 = int(rng.integers(ticks // 2, int(ticks * 0.8)))
    w1 = SkewWindow(s1, s1 + int(rng.integers(25, 40)), draw_incs())
    crash = CrashEvent(int(rng.integers(ticks // 3, ticks // 2)))
    return ChaosSchedule(seed=seed, ticks=ticks, skews=(w0, w1),
                         crashes=(crash,))


def generate_enospc(seed: int, ticks: int = 140,
                    peers: int = 3) -> ChaosSchedule:
    """Disk-full on WAL append (fused plane): two ENOSPC write failures
    on seeded peers/ops — each is fatal (crash + restart from a clean
    tail), and the consumed trigger lets the retry land."""
    rng = np.random.default_rng(seed ^ 0xE05)
    faults = tuple(EnospcFault(int(rng.integers(0, peers)),
                               int(rng.integers(20, 60)) + 60 * i)
                   for i in range(2))
    return ChaosSchedule(seed=seed, ticks=ticks, enospc_faults=faults,
                         prop_rate=0.6)


def generate_stall(seed: int, ticks: int = 120,
                   peers: int = 3) -> ChaosSchedule:
    """Fsync latency stalls (fused plane): bursts of slow fsyncs on two
    seeded peers, plus a crash mid-run — durability and ordering must
    hold when the barrier is merely LATE."""
    rng = np.random.default_rng(seed ^ 0x57A)
    stalls = tuple(FsyncStall(int(rng.integers(0, peers)),
                              int(rng.integers(10, 40)) + 40 * i,
                              count=3, stall_s=0.02)
                   for i in range(2))
    crash = CrashEvent(int(rng.integers(ticks // 2, int(ticks * 0.8))))
    return ChaosSchedule(seed=seed, ticks=ticks, fsync_stalls=stalls,
                         crashes=(crash,), prop_rate=0.6)


def generate_compact(seed: int, ticks: int = 200,
                     peers: int = 3) -> ChaosSchedule:
    """Aggressive compaction interleaved with crashes (fused plane):
    compact every few ticks with a tiny retained window while crashes
    (one power loss with a torn record) land between floors — restart
    replays COMPACT-marked, segment-dropped WALs.  Pair with a small
    cfg log_window (the runner's compact clamps keep to it)."""
    rng = np.random.default_rng(seed ^ 0xC04)
    lo, hi = int(ticks * 0.3), int(ticks * 0.9)
    t0, t1 = sorted(int(t) for t in rng.choice(
        np.arange(lo, hi), size=2, replace=False))
    crashes = (CrashEvent(t0), CrashEvent(t1))
    # A mid-record power loss (NOT on a tick boundary — boundary
    # crashes have nothing unsynced to tear) so torn-tail repair runs
    # against a compacted, COMPACT-marked WAL.
    torn = (TornWriteFault(int(rng.integers(0, peers)),
                           int(rng.integers(120, 240))),)
    return ChaosSchedule(seed=seed, ticks=ticks, crashes=crashes,
                         torn_writes=torn,
                         compact_every=int(rng.integers(6, 12)),
                         compact_keep=1, prop_rate=0.9, read_rate=0.3)


def generate_corrupt_plan(seed: int, ticks: int = 260,
                          peers: int = 3) -> NodeChaosPlan:
    """Byzantine/corrupted payloads (lockstep wire plane): windows of
    seeded frame corruption — the CRC framing must drop every mangled
    frame (counted), and consensus must ride out the loss."""
    rng = np.random.default_rng(seed ^ 0xC0F)
    warmup = 50
    wins = []
    for i in range(2):
        s = int(rng.integers(warmup + i * ticks // 3,
                             warmup + 20 + i * ticks // 3))
        wins.append(CorruptWindow(s, s + int(rng.integers(30, 50)),
                                  float(rng.uniform(0.15, 0.4))))
    c0 = int(rng.integers(ticks // 2, int(ticks * 0.8)))
    return NodeChaosPlan(seed=seed, ticks=ticks,
                         corruptions=tuple(wins),
                         crashes=(NodeCrash(c0, int(rng.integers(0, peers)),
                                            down=int(rng.integers(25, 40))),))


def generate_snapshot_plan(seed: int, ticks: int = 340,
                           peers: int = 3) -> NodeChaosPlan:
    """Aggressive compaction + InstallSnapshot + crash interleaving
    (lockstep RaftNode plane): every node compacts on a short cadence
    while one follower is crashed long enough to fall below every
    retained floor — its restart must be served by a full state
    transfer, and a second, leader-targeted crash lands while transfers
    are in flight.  After a fault-free heal window the survivors must
    CONVERGE (identical applied state per group)."""
    rng = np.random.default_rng(seed ^ 0x5A7)
    lag_peer = int(rng.integers(0, peers))
    c0 = int(rng.integers(50, 70))
    down = int(rng.integers(150, 190))
    c1 = int(rng.integers(c0 + down + 20, ticks - 40))
    crashes = (NodeCrash(c0, lag_peer, down=down),
               NodeCrash(c1, LEADER_TARGET, down=int(rng.integers(20, 30))))
    return NodeChaosPlan(seed=seed, ticks=ticks, crashes=crashes,
                         compact_every=int(rng.integers(6, 10)),
                         compact_keep=1, heal_ticks=80, prop_rate=0.95)


def generate_tcp_plan(seed: int, ticks: int = 200,
                      peers: int = 3) -> TcpChaosPlan:
    """Chaos under the REAL TCP transport: seeded send-side drops, a
    one-directional (asymmetric) block window, frame corruption, and
    delayed frames — followed by a heal window over which the cluster
    must converge and commit."""
    rng = np.random.default_rng(seed ^ 0x7C9)
    warmup = 40
    s0 = int(rng.integers(warmup, ticks // 3))
    drops = (DropWindow(s0, s0 + int(rng.integers(25, 40)),
                        float(rng.uniform(0.1, 0.25))),)
    s1 = int(rng.integers(ticks // 3, 2 * ticks // 3))
    corr = (CorruptWindow(s1, s1 + int(rng.integers(30, 45)),
                          float(rng.uniform(0.2, 0.4))),)
    src = int(rng.integers(0, peers))
    dst = int((src + 1 + rng.integers(0, peers - 1)) % peers)
    s2 = int(rng.integers(2 * ticks // 3, ticks - 25))
    asym = (AsymPartitionWindow(s2, s2 + int(rng.integers(20, 30)),
                                src, dst),)
    d0 = int(rng.integers(warmup, ticks - 40))
    delays = (DelayWindow(d0, d0 + int(rng.integers(20, 35)),
                          float(rng.uniform(0.1, 0.25)),
                          int(rng.integers(5, 15))),)   # milliseconds
    return TcpChaosPlan(seed=seed, ticks=ticks, drops=drops,
                        corruptions=corr, asym_partitions=asym,
                        delays=delays)


def generate_membership_plan(seed: int, ticks: int = 320,
                             peers: int = 4) -> MembershipChaosPlan:
    """The node-replacement story under faults, seeded: a 3-voter
    cluster over `peers` provisioned slots loses a voter to a
    PERMANENT kill (SIGKILL, never restarted), boots the spare slot as
    a fresh machine, adds it as a learner, promotes it once caught up
    (joint consensus), and removes the dead member — while a drop
    window and a second (transient) crash land mid-churn.  After the
    heal window the cluster must run on the replacement voter set with
    every invariant intact, including RemovedQuorumSafety."""
    rng = np.random.default_rng(seed ^ 0x3E3)
    spare = peers - 1
    dead = int(rng.integers(0, 3))           # the voter that dies
    kill_t = int(rng.integers(50, 70))
    boot_t = kill_t + int(rng.integers(5, 15))
    add_t = boot_t + int(rng.integers(5, 10))
    promote_t = add_t + int(rng.integers(30, 50))
    remove_t = promote_t + int(rng.integers(30, 50))
    # A transient crash of a SURVIVING voter while the learner catches
    # up, and a drop window across the promote.
    surv = [p for p in range(3) if p != dead]
    c1 = int(rng.integers(add_t + 5, promote_t))
    crashes = (NodeCrash(kill_t, dead, down=10 * ticks),   # permanent
               NodeCrash(c1, surv[int(rng.integers(0, 2))],
                         down=int(rng.integers(15, 25))))
    d0 = promote_t - int(rng.integers(5, 15))
    drops = (DropWindow(d0, d0 + int(rng.integers(15, 30)),
                        float(rng.uniform(0.05, 0.15))),)
    final = tuple(sorted(surv + [spare]))
    return MembershipChaosPlan(
        seed=seed, ticks=max(ticks, remove_t + 60), peers=peers,
        initial_voters=(0, 1, 2), initial_down=(spare,),
        boots=(NodeBoot(boot_t, spare),),
        events=(MemberEvent(add_t, "add_learner", spare),
                MemberEvent(promote_t, "promote", spare),
                MemberEvent(remove_t, "remove", dead)),
        crashes=crashes, drops=drops, heal_ticks=80,
        final_voters=final)


def generate_tcp_rebind_plan(seed: int, ticks: int = 180,
                             peers: int = 3) -> TcpRebindPlan:
    """TCP crash/restart with port rebinding (ROADMAP chaos frontier):
    one leader-targeted and one random-follower stop/rebind, spaced so
    the second fires after the first recovered."""
    rng = np.random.default_rng(seed ^ 0x4EB)
    t0 = int(rng.integers(50, 70))
    d0 = int(rng.integers(20, 30))
    t1 = int(rng.integers(t0 + d0 + 20, ticks - 30))
    restarts = (NodeCrash(t0, LEADER_TARGET, down=d0),
                NodeCrash(t1, int(rng.integers(0, peers)),
                          down=int(rng.integers(15, 25))))
    return TcpRebindPlan(seed=seed, ticks=ticks, restarts=restarts)


def generate_node_plan(seed: int, ticks: int = 320,
                       peers: int = 3) -> NodeChaosPlan:
    """Threaded-cluster plan: one leader-targeted kill, one follower
    kill, one partition window — the reference's stop/restart scenarios
    (raftsql_test.go:117-170) as a seeded schedule."""
    rng = np.random.default_rng(seed)
    warmup = 50
    p0 = int(rng.integers(warmup, ticks // 3))
    parts = (PartitionWindow(p0, p0 + int(rng.integers(25, 45)),
                             int(rng.integers(0, peers))),)
    c0 = int(rng.integers(ticks // 3, ticks // 2))
    c1 = int(rng.integers(ticks // 2 + 20, int(ticks * 0.8)))
    crashes = (NodeCrash(c0, LEADER_TARGET, down=int(rng.integers(25, 40))),
               NodeCrash(c1, int(rng.integers(0, peers)),
                         down=int(rng.integers(25, 40))))
    return NodeChaosPlan(seed=seed, ticks=ticks, partitions=parts,
                         crashes=crashes)


# ---------------------------------------------------------------------------
# Elastic keyspace: the reshard nemesis (PR 16)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ReshardEvent:
    """One reshard verb requested at `tick` (retried each tick while the
    coordinator is busy).  `src`/`dst` < 0 are resolved at issue time
    from live state — deterministically, since the runner's state is
    seed-determined:

      split:   src -1 = group owning the most slots; dst -1 = a retired
               group if one exists, else the group owning the fewest
               slots; `move_slots` slots move (acked-key-bearing slots
               first, so the verb always has data to prove itself on).
      merge:   src -1 = group owning the fewest slots; dst -1 = group
               owning the most slots (never src).
      migrate: src -1 = lowest live group; dst is a PEER (-1 = the
               group leader's successor slot).
    """
    tick: int
    verb: str
    src: int = -1
    dst: int = -1
    move_slots: int = 2


@dataclasses.dataclass(frozen=True)
class ReshardNemesisPlan:
    """Scripted elastic-keyspace attack (fused plane,
    chaos/scenarios.py ReshardChaosRunner): seeded split/merge/migrate
    schedules race partitions, message drops, whole-cluster crash+
    restart, coordinator SIGKILL mid-verb, and disk faults on the
    snapshot-fork ship path, under live acked-PUT load — checked by
    NoAckedWriteLost (every acked write readable in exactly one
    post-reshard group, WAL-fold post-mortem included) and
    NoAvailabilityLoss (writes outside the moving range never stall
    past a bound; verbs always resolve) on top of the standing
    election-safety / durability / linearizability invariants.

    A SEPARATE plan class on purpose (ReadNemesisPlan precedent):
    extending ChaosSchedule would change the asdict() digest of every
    existing family.  The runner projects the fault fields into a
    ChaosSchedule internally so fault application shares the proven
    code paths.

    `broken_flip=True` builds the deliberately broken coordinator that
    journals the copy fence and flips the router WITHOUT waiting for
    the destination group to apply the copied rows — the falsification
    variant NoAckedWriteLost must CATCH.  `part_group` anchors
    LEADER_TARGET partition windows on that group's leader (the
    directed plan aims them at the split's destination group to starve
    the copy path).  `presplit_transfer=True` moves the destination
    group's leadership off the source group's leader during warmup so
    the directed partition stalls ONLY the copy path."""
    seed: int
    ticks: int
    peers: int = 3
    groups: int = 4
    nslots: int = 16
    keys: int = 16
    reshards: Tuple[ReshardEvent, ...] = ()
    # Ticks at which the coordinator process is SIGKILLed; a fresh
    # coordinator recovers from the journal fold `down` ticks later.
    coordinator_kills: Tuple[int, ...] = ()
    coordinator_down_ticks: int = 6
    drops: Tuple[DropWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    asym_partitions: Tuple[AsymPartitionWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    election_ticks: int = 10
    part_group: int = 0
    presplit_transfer: bool = False
    # fsync ordinal (0-based) on the migrate ship path to disk-fault;
    # -1 = no fork fault.  The faulted migrate must ABORT cleanly.
    fork_fault_op: int = -1
    # A verb still unresolved this many ticks after issue is an
    # availability violation (generous: covers coordinator kills and
    # directed copy starvation windows).
    verb_deadline_ticks: int = 220
    # Probe writes to keys OUTSIDE the moving range, armed in quiet air
    # while a verb is active, must commit within this bound.
    probe_ticks: int = 30
    probe_every: int = 12
    retry_steps: int = 40
    broken_flip: bool = False
    prop_rate: float = 0.7
    read_rate: float = 0.25

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_reshard(seed: int, ticks: int = 520,
                     peers: int = 3) -> ReshardNemesisPlan:
    """The reshard-under-nemesis family: a split whose coordinator is
    SIGKILLed mid-verb (recovery must resume or abort cleanly from the
    journal), a merge racing a leader-targeted partition, a migrate in
    clean air (must complete through the catch-up-gated transfer
    kernel), a second migrate whose snapshot ship hits a disk fault
    (must abort cleanly), a whole-cluster crash, and a post-crash split
    whose coordinator is killed AFTER the copy fence (recovery must
    resume FORWARD through flip+cleanup) — all under acked-PUT load."""
    rng = np.random.default_rng(seed ^ 0x2E54)
    warmup = 50
    split1 = ReshardEvent(warmup + 10, "split",
                          move_slots=int(rng.integers(2, 4)))
    kill1 = split1.tick + 8                   # mid-verb, pre-fence-ish
    merge = ReshardEvent(150, "merge")
    part = PartitionWindow(merge.tick + 4,
                           merge.tick + 4 + int(rng.integers(18, 26)),
                           LEADER_TARGET)
    mig1 = ReshardEvent(230, "migrate")
    drop0 = int(rng.integers(268, 276))
    drop = DropWindow(drop0, drop0 + int(rng.integers(14, 22)),
                      float(rng.uniform(0.08, 0.18)))
    mig2 = ReshardEvent(300, "migrate")       # ship disk-faulted: abort
    crash = CrashEvent(340)
    split2 = ReshardEvent(368, "split",
                          move_slots=int(rng.integers(2, 4)))
    kill2 = split2.tick + 12                  # post-fence: resume forward
    return ReshardNemesisPlan(
        seed=seed, ticks=max(ticks, split2.tick + 150), peers=peers,
        reshards=(split1, merge, mig1, mig2, split2),
        coordinator_kills=(kill1, kill2),
        drops=(drop,), partitions=(part,), crashes=(crash,),
        fork_fault_op=1)


def falsification_reshard_plan(seed: int = 0,
                               broken: bool = True) -> ReshardNemesisPlan:
    """DIRECTED reshard-falsification scenario: a split moves two
    acked-key-bearing slots from group 0 to group 2 while a
    leader-targeted partition (anchored on group 2, the DESTINATION)
    stalls the copy path — after a warmup transfer made sure group 2's
    leader is not group 0's leader, so the source group's journal keeps
    committing.  The CORRECT coordinator waits out the partition behind
    the copy fence and flips only after group 2 applied every copied
    row: the verb completes.  broken=True flips the router the moment
    the copies are PROPOSED: the freshly-flipped owner serves the moved
    keys from an empty shard, and NoAckedWriteLost MUST fire on the
    identical schedule — proving the harness detects a premature
    router flip, not chaos in general."""
    part = PartitionWindow(58, 140, LEADER_TARGET)
    split = ReshardEvent(60, "split", src=0, dst=2, move_slots=2)
    return ReshardNemesisPlan(
        seed=seed, ticks=300, peers=3, groups=4,
        reshards=(split,), partitions=(part,),
        election_ticks=16, part_group=2, presplit_transfer=True,
        verb_deadline_ticks=250, broken_flip=broken,
        prop_rate=1.0, read_rate=0.2)


@dataclasses.dataclass(frozen=True)
class PodKill:
    """SIGKILL pod process `proc` once its progress file shows it past
    workload iteration `at_iter` — the whole-host crash.  The pod's
    fail-stop contract turns one host's death into a pod-wide abort
    (surviving processes exit on PodPeerLost), so each kill ends its
    INCARNATION: the nemesis respawns all N processes, which rebuild
    the global state from the merged cross-host replay exchange."""
    incarnation: int
    at_iter: int
    proc: int


@dataclasses.dataclass(frozen=True)
class PodLinkCut:
    """Cut the PROPOSE plane at process `origin` for workload
    iterations [start, end) of incarnation `incarnation`: the origin
    defers its client offers (they cannot reach the collective) while
    still serving its collective role — availability degrades at one
    host without violating any promise.  A TRANSPORT-level cut is
    deliberately not a separate event: the pod is fail-stop, so a
    severed collective socket is indistinguishable from a host kill
    (PodPeerLost, pod-wide abort) and the PodKill events already
    exercise that path on the surviving side."""
    incarnation: int
    start: int
    end: int
    origin: int


@dataclasses.dataclass(frozen=True)
class PodChaosPlan:
    """Scripted scenario for a REAL multi-process pod (chaos/pod.py:
    N `raftsql_tpu.chaos.pod --child` processes lockstepped by the
    TcpPodTransport collective, sharded WAL dirs per host).

    A SEPARATE plan class on purpose (ReadNemesisPlan precedent):
    extending an existing plan would change the asdict() digest of
    every committed family.  Determinism tier matches the proc plane
    (the weakest): the PLAN is a pure function of the seed
    (digest-compared) and the invariant VERDICTS must reproduce, but
    the committed history crosses real kernel scheduling across N
    processes and is not bit-reproducible.

    `unsafe_ack` + `crash_at` are the FALSIFICATION knobs: the child
    acknowledges writes at OFFER time (before any durability) and
    hard-exits at iteration `crash_at` of incarnation 0 — the
    durability invariant MUST then catch acked writes missing from the
    final fold, and the same schedule with unsafe_ack=False must pass.
    """
    seed: int
    ticks: int
    procs: int = 2
    peers: int = 3
    groups: int = 4
    group_shards: int = 2
    settle_ticks: int = 10
    kills: Tuple[PodKill, ...] = ()
    cuts: Tuple[PodLinkCut, ...] = ()
    unsafe_ack: bool = False
    crash_at: int = -1

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_pod(seed: int, ticks: int = 60) -> PodChaosPlan:
    """The pod nemesis family (`make chaos-pod`): a 2-process pod
    (proc 0 coordinates the collective; each process owns one of two
    group shards) runs three incarnations of an acked-write workload:

      incarnation 0 — a propose-plane cut at one origin, then SIGKILL
      of the NON-coordinator host after the cut healed (the survivor
      is the coordinator: it must abort pod-wide, not hang);
      incarnation 1 — SIGKILL of the COORDINATOR host (the survivor's
      socket breaks mid-collective: PodPeerLost, fail-fast);
      incarnation 2 — fault-free: finish the workload, settle, and
      dump the audit fold every invariant is checked against.

    Kill iterations and the cut window are seeded; every event is
    guaranteed to fire (kills wait for the target's progress file, the
    cut window closes before incarnation 0's kill)."""
    rng = np.random.default_rng(seed ^ 0xD0D)
    c0 = int(rng.integers(8, 14))
    cut = PodLinkCut(0, c0, c0 + int(rng.integers(6, 10)),
                     origin=int(rng.integers(0, 2)))
    k0 = PodKill(0, cut.end + int(rng.integers(4, 10)), proc=1)
    k1 = PodKill(1, int(rng.integers(12, ticks - 10)), proc=0)
    return PodChaosPlan(seed=seed, ticks=ticks, procs=2, peers=3,
                        groups=4, group_shards=2,
                        kills=(k0, k1), cuts=(cut,))


@dataclasses.dataclass(frozen=True)
class ReplicaFault:
    """One scripted fault against the read-replica tier at plan time
    `t_ms`, aimed at replica index `target`:

      cut      — partition the replica's stream subscription (its
                 runner-owned TCP proxy blackholes; the HTTP plane
                 stays up, so the fail-closed ladder is what's tested);
      heal     — end the partition;
      kill     — SIGKILL the replica process mid-stream;
      restart  — respawn it (fresh state: bootstrap via log replay or
                 fresh-base RESYNC);
      corrupt  — flip one bit in the next upstream->replica chunk (the
                 CRC must catch it; the replica drops + resubscribes).
    """
    t_ms: int
    kind: str
    target: int


@dataclasses.dataclass(frozen=True)
class ReplicaChaosPlan:
    """Scripted scenario for the read-replica tier (chaos/replica.py:
    one fused engine with --replica-listen, N real replica processes
    subscribed through runner-owned proxies).  A SEPARATE plan class
    (ReadNemesisPlan precedent): extending an existing plan would
    change the asdict() digest of every committed family.  Determinism
    tier matches the proc plane: the plan is a pure function of the
    seed and the invariant VERDICTS must reproduce; the history
    crosses real kernels and processes and is not bit-stable.

    `unsafe_serve` is the FALSIFICATION knob: the replica boots with
    its session/linear fail-closed gates disabled, so under a stream
    cut it serves below acked watermarks and past its lease horizon —
    the StaleReadNever invariant MUST catch it, and the same schedule
    with the gates on must pass."""
    seed: int
    replicas: int = 2
    groups: int = 2
    duration_ms: int = 4000
    writer_ms: int = 25
    settle_ms: int = 1500
    faults: Tuple[ReplicaFault, ...] = ()
    unsafe_serve: bool = False

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_replica(seed: int,
                     duration_ms: int = 4000) -> ReplicaChaosPlan:
    """The replica-tier nemesis family (`make chaos-replica`): two
    replicas of a two-group fused engine take, in seeded order,

      * a stream partition + heal at replica 0 (the fail-closed ladder
        must refuse — never serve stale — while cut, and the resumed
        subscription must replay or resync the gap);
      * SIGKILL + respawn of replica 1 mid-stream (fresh-state
        bootstrap under load);
      * one flipped bit in replica 0's subscription (the frame CRC
        must surface it as a typed corruption: drop + resubscribe,
        never a wrong row).

    Writers keep acking through the engine the whole time; every
    session/linear probe a replica ANSWERS is checked against the
    rows acked at the probe's watermark (StaleReadNever)."""
    rng = np.random.default_rng(seed ^ 0x5EB1)
    cut0 = int(rng.integers(500, 900))
    heal0 = cut0 + int(rng.integers(500, 800))
    kill1 = int(rng.integers(1300, 1700))
    restart1 = kill1 + int(rng.integers(300, 500))
    corrupt0 = int(rng.integers(2400, 2800))
    faults = (ReplicaFault(cut0, "cut", 0),
              ReplicaFault(heal0, "heal", 0),
              ReplicaFault(kill1, "kill", 1),
              ReplicaFault(restart1, "restart", 1),
              ReplicaFault(corrupt0, "corrupt", 0))
    return ReplicaChaosPlan(seed=seed, replicas=2, groups=2,
                            duration_ms=duration_ms, faults=faults)


def falsification_replica_plan(seed: int = 0,
                               broken: bool = True) -> ReplicaChaosPlan:
    """DIRECTED stale-replica falsification: one replica, one group, a
    stream cut that never heals — the writer keeps acking through the
    engine while the replica's fold freezes.  broken=True disables the
    replica's session/linear gates (--unsafe-serve): it then serves
    reads below the acked watermark and linear reads past its lease
    horizon, and StaleReadNever MUST catch the first one.  The SAME
    schedule with the gates on refuses (421) instead and must pass —
    proving the harness detects exactly a gate that fails open, not
    partitions in general."""
    return ReplicaChaosPlan(
        seed=seed, replicas=1, groups=1, duration_ms=2000,
        writer_ms=25, faults=(ReplicaFault(500, "cut", 0),),
        unsafe_serve=broken)


def falsification_pod_plan(seed: int = 0,
                           broken: bool = True) -> PodChaosPlan:
    """DIRECTED pod-durability falsification: no kills, no cuts — one
    short incarnation that crashes (hard exit, before any further
    durable phase) at a fixed iteration, then the audit incarnation.
    broken=True acks every write at OFFER time (before the collective,
    before any fsync): the writes acked in the iterations right before
    the crash were never committed anywhere, and the durability
    invariant MUST catch them missing from the audit fold.  The SAME
    schedule with honest acks must pass — proving the harness detects
    exactly the premature ack, not pod restarts in general."""
    return PodChaosPlan(seed=seed, ticks=24, procs=2, peers=3,
                        groups=4, group_shards=2,
                        unsafe_ack=broken, crash_at=12)


@dataclasses.dataclass(frozen=True)
class OverloadBurst:
    """An offered-load burst: `extra` additional open-loop writes per
    tick while the window is active, on top of the plan's baseline."""
    start: int
    end: int
    extra: int


@dataclasses.dataclass(frozen=True)
class OverloadNemesisPlan:
    """Scripted overload attack (fused plane, chaos/scenarios.py
    OverloadChaosRunner): an OPEN-LOOP workload offers far more writes
    per tick than the engine can drain (offered >> capacity), in
    bursts, with hot-group skew and slow-fsync stalls — while the
    bounded admission controller (raftsql_tpu/overload/) is the only
    thing standing between the propose queues and unbounded memory.

    A SEPARATE plan class on purpose (same rule as every other
    family): extending ChaosSchedule would change the asdict() digest
    of every committed family.  The runner projects the fault fields
    into a ChaosSchedule internally and drives the offered load
    itself.

    `unsafe_no_admission` is the falsification seam: the runner then
    attaches NO controller, and the OVERLOAD-MEMORY invariant (propose
    backlog > total_cap, measured against the engine's actual queues
    every tick) MUST catch the identical schedule that the bounded
    control survives."""
    seed: int
    ticks: int
    groups: int = 4
    peers: int = 3
    group_cap: int = 24
    total_cap: int = 48
    offered_per_tick: int = 32      # ~2x the 4-group x 4-entry drain
    hot_group: int = 0
    hot_share: float = 0.5          # P(an offered write hits hot_group)
    deadline_rate: float = 0.4      # P(a write carries a device-step
    deadline_lo: int = 1            # deadline drawn in [lo, hi])
    deadline_hi: int = 8
    read_rate: float = 0.3
    bursts: Tuple[OverloadBurst, ...] = ()
    fsync_stalls: Tuple[FsyncStall, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    unsafe_no_admission: bool = False
    # Acceptance floors (checked by chaos/run.py, not invariants):
    # committed >= goodput_floor * ticks despite 2x offered load, and
    # every group commits >= starvation_floor entries (no group is
    # starved by the hot group's pressure).
    goodput_floor: int = 2
    starvation_floor: int = 8

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate_overload(seed: int, ticks: int = 160) -> OverloadNemesisPlan:
    """The overload nemesis family: sustained 2x offered load with two
    burst windows (3x+), hot-group skew, two slow-fsync stall windows
    (latency pressure, not failure), and one whole-cluster
    crash+restart mid-overload so the durability audit replays WALs
    written under admission pressure.  Admission is ON: the propose
    backlog must never exceed total_cap, acked writes must survive the
    restart, goodput must clear the floor and no group may starve —
    and two runs must produce identical plan + result digests."""
    rng = np.random.default_rng(seed ^ 0x10AD)
    warmup = 30
    b0 = int(rng.integers(warmup, warmup + ticks // 4))
    b1 = int(rng.integers(ticks // 2, int(ticks * 0.7)))
    bursts = (OverloadBurst(b0, b0 + int(rng.integers(10, 20)),
                            int(rng.integers(16, 33))),
              OverloadBurst(b1, b1 + int(rng.integers(10, 20)),
                            int(rng.integers(16, 33))))
    stalls = (FsyncStall(int(rng.integers(0, 3)),
                         int(rng.integers(40, 80)), count=4,
                         stall_s=0.01),
              FsyncStall(int(rng.integers(0, 3)),
                         int(rng.integers(120, 180)), count=4,
                         stall_s=0.01))
    crash = CrashEvent(int(rng.integers(int(ticks * 0.55),
                                        int(ticks * 0.8))))
    return OverloadNemesisPlan(
        seed=seed, ticks=ticks, hot_group=int(rng.integers(0, 4)),
        bursts=bursts, fsync_stalls=stalls, crashes=(crash,))


def falsification_overload_plan(seed: int = 0,
                                broken: bool = True
                                ) -> OverloadNemesisPlan:
    """DIRECTED unbounded-memory falsification: sustained 2x offered
    load, no other faults.  broken=True attaches NO admission
    controller (unsafe_no_admission): the open-loop producer outruns
    the drain by ~16 entries/tick, so the propose backlog crosses
    total_cap within a few ticks and the OVERLOAD-MEMORY invariant
    MUST catch it.  The SAME schedule with the bounded controller
    must pass — proving the harness detects exactly the missing
    admission bound, not offered load in general."""
    return OverloadNemesisPlan(
        seed=seed, ticks=80, deadline_rate=0.0, read_rate=0.0,
        unsafe_no_admission=broken,
        goodput_floor=1, starvation_floor=1)
