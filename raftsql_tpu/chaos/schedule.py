"""Seeded, deterministic, tick-indexed fault schedules.

A schedule is a frozen dataclass: every fault the scenario will inject
— message-drop windows, delay windows, peer partitions, crash/restart
events, storage fsync faults — pinned to tick indexes before the run
starts.  `generate(seed)` derives one from a single integer seed via
`numpy.random.default_rng`, so any failure reproduces from its seed
alone; `digest()` hashes the canonical form so `make chaos` can prove
two runs of one seed saw the identical schedule.

"Paxos vs Raft" (arXiv:2004.05074) argues raft's safety claims only
mean something under adversarial schedules of partitions and crashes;
this module is where those schedules come from.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Tuple

import numpy as np

# Partition / crash target sentinel: resolved at the window's first tick
# to whichever peer then leads group 0 — the leader-targeted kill.
LEADER_TARGET = -2


@dataclasses.dataclass(frozen=True)
class DropWindow:
    """Drop each message slot independently with probability p while
    start <= tick < end (transport.faults.random_drop)."""
    start: int
    end: int
    p: float


@dataclasses.dataclass(frozen=True)
class DelayWindow:
    """Hold each message slot with probability p for `latency` ticks
    before delivery (transport.faults.hold_messages/release_messages).
    Messages still in flight at a crash are lost — as on a real wire."""
    start: int
    end: int
    p: float
    latency: int


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """Isolate one peer (nothing in, nothing out) for the window.
    peer == LEADER_TARGET resolves to group 0's leader at `start`."""
    start: int
    end: int
    peer: int


@dataclasses.dataclass(frozen=True)
class CrashEvent:
    """Hard process crash at `tick` (the whole fused cluster process),
    followed by immediate restart-from-WAL.  power_loss=True models a
    machine crash instead: everything not fsynced is dropped, and
    `tear_peer` (if >= 0) additionally has its last WAL write torn
    mid-record.  Scheduled crashes fire on tick boundaries (post-
    barrier); MID-tick power loss comes from TornWriteFault."""
    tick: int
    power_loss: bool = False
    tear_peer: int = -1


@dataclasses.dataclass(frozen=True)
class FsyncFault:
    """The op-th fsync under peer `peer`'s WAL directory raises (a
    failed disk flush).  The runner treats it as fatal for the process
    — crash + restart — which is the etcd posture (panic on WAL sync
    failure rather than ack unsynced data)."""
    peer: int
    op: int


@dataclasses.dataclass(frozen=True)
class TornWriteFault:
    """Power loss mid-way through peer `peer`'s op-th WAL record write:
    the machine dies with the record partially in the page cache and
    nothing of the current tick fsynced.  The runner tears that record
    (truncates it mid-write), drops every other file's unsynced tail,
    and restarts — WAL._repair_tail and epoch repair must recover."""
    peer: int
    op: int


@dataclasses.dataclass(frozen=True)
class ChaosSchedule:
    """A complete scripted scenario for the fused runtime."""
    seed: int
    ticks: int
    drops: Tuple[DropWindow, ...] = ()
    delays: Tuple[DelayWindow, ...] = ()
    partitions: Tuple[PartitionWindow, ...] = ()
    crashes: Tuple[CrashEvent, ...] = ()
    fsync_faults: Tuple[FsyncFault, ...] = ()
    torn_writes: Tuple[TornWriteFault, ...] = ()
    prop_rate: float = 0.5       # P(issue a PUT batch) per tick
    read_rate: float = 0.35      # P(issue a linearizable GET) per tick

    def describe(self) -> dict:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        blob = json.dumps(self.describe(), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


@dataclasses.dataclass(frozen=True)
class NodeCrash:
    """Threaded-cluster plane: kill peer `peer` (0-based, or
    LEADER_TARGET) at `tick`, restart it `down` ticks later."""
    tick: int
    peer: int
    down: int = 30


@dataclasses.dataclass(frozen=True)
class NodeChaosPlan:
    """Scripted scenario for the lockstep RaftNode cluster."""
    seed: int
    ticks: int
    partitions: Tuple[PartitionWindow, ...] = ()
    crashes: Tuple[NodeCrash, ...] = ()
    prop_rate: float = 0.4

    def digest(self) -> str:
        blob = json.dumps(dataclasses.asdict(self), sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


def generate(seed: int, ticks: int = 240, peers: int = 3,
             min_partitions: int = 2, min_crashes: int = 2,
             min_fsync_faults: int = 1,
             min_torn_writes: int = 1,
             with_delays: bool = True) -> ChaosSchedule:
    """Derive a full scenario from one seed.

    Guarantees the floors the acceptance gate needs: >= min_partitions
    partition windows (at least one leader-targeted), >= min_crashes
    crash/restart events, >= min_fsync_faults injected fsync failures,
    and >= min_torn_writes mid-write power losses (each also a
    crash/restart).
    """
    rng = np.random.default_rng(seed)
    warmup = 40                          # let first elections settle

    n_part = int(min_partitions + rng.integers(0, 2))
    parts = []
    for i in range(n_part):
        start = int(rng.integers(warmup, max(warmup + 1,
                                             ticks - 60)))
        length = int(rng.integers(20, 41))
        # First window is always the leader-targeted kill.
        peer = LEADER_TARGET if i == 0 else int(rng.integers(0, peers))
        parts.append(PartitionWindow(start, min(start + length, ticks),
                                     peer))
    parts.sort(key=lambda w: w.start)

    n_crash = int(min_crashes + rng.integers(0, 2))
    lo, hi = int(ticks * 0.35), int(ticks * 0.9)
    crash_ticks = sorted(int(t) for t in rng.choice(
        np.arange(lo, hi), size=n_crash, replace=False))
    # Scheduled crashes land on tick boundaries, where the durable
    # barrier has just completed — they exercise clean process-kill
    # replay.  Power-loss recovery (unsynced/torn tails) is exercised
    # by the torn-write faults below, which fire MID-tick.
    crashes = tuple(CrashEvent(t) for t in crash_ticks)

    # Each active tick fsyncs every peer once, so op counts in the low
    # tens always fire well before the first crash window.
    faults = tuple(FsyncFault(int(rng.integers(0, peers)),
                              int(rng.integers(15, 30)) + 10 * i)
                   for i in range(min_fsync_faults))
    # Every active tick writes at least a hard-state record per peer;
    # write ops accumulate a few per active tick, so these fire mid-run.
    torn = tuple(TornWriteFault(int(rng.integers(0, peers)),
                                int(rng.integers(60, 120)) + 40 * i)
                 for i in range(min_torn_writes))

    drops = (DropWindow(int(rng.integers(warmup, ticks // 2)),
                        int(rng.integers(ticks // 2, ticks)),
                        float(rng.uniform(0.05, 0.2))),)
    delays = ()
    if with_delays:
        d0 = int(rng.integers(warmup, ticks - 40))
        delays = (DelayWindow(d0, d0 + int(rng.integers(20, 40)),
                              float(rng.uniform(0.1, 0.3)),
                              int(rng.integers(2, 5))),)

    return ChaosSchedule(seed=seed, ticks=ticks, drops=drops,
                         delays=delays, partitions=tuple(parts),
                         crashes=crashes, fsync_faults=faults,
                         torn_writes=torn)


def generate_node_plan(seed: int, ticks: int = 320,
                       peers: int = 3) -> NodeChaosPlan:
    """Threaded-cluster plan: one leader-targeted kill, one follower
    kill, one partition window — the reference's stop/restart scenarios
    (raftsql_test.go:117-170) as a seeded schedule."""
    rng = np.random.default_rng(seed)
    warmup = 50
    p0 = int(rng.integers(warmup, ticks // 3))
    parts = (PartitionWindow(p0, p0 + int(rng.integers(25, 45)),
                             int(rng.integers(0, peers))),)
    c0 = int(rng.integers(ticks // 3, ticks // 2))
    c1 = int(rng.integers(ticks // 2 + 20, int(ticks * 0.8)))
    crashes = (NodeCrash(c0, LEADER_TARGET, down=int(rng.integers(25, 40))),
               NodeCrash(c1, int(rng.integers(0, peers)),
                         down=int(rng.integers(25, 40))))
    return NodeChaosPlan(seed=seed, ticks=ticks, partitions=parts,
                         crashes=crashes)
