"""Replica-tier chaos: a seeded nemesis over REAL replica processes.

The read-replica tier (raftsql_tpu/replica/) promises exactly one
thing under fire: a replica NEVER answers a session or linear read
with data staler than the mode's contract — it refuses (421, toward
the authoritative tier) instead.  This nemesis attacks that promise
with a fused engine (`--replica-listen`), N `python -m
raftsql_tpu.replica` processes, and a runner-owned TCP proxy in front
of each replica's stream subscription so the nemesis can:

  * CUT the subscription (blackhole the proxy) — the replica's fold
    freezes while the engine keeps acking writes; every session probe
    carrying a fresh watermark must refuse until the HEAL, and the
    resumed subscription must replay or resync the gap;
  * SIGKILL a replica mid-stream and RESPAWN it — fresh-state
    bootstrap (log replay below the head, fresh-base RESYNC above it)
    while the writer keeps moving;
  * CORRUPT one bit of the stream — the frame CRC must surface it as
    a typed fault (drop + resubscribe), never a wrong row.

Workload: a single-threaded deterministic loop writes acked rows
through the engine (per-group counts + the X-Raft-Session watermark
each ack returned), interleaves the fault timeline, and probes every
replica's HTTP plane in session and linear mode.  The StaleReadNever
invariant: a 200 session answer must reflect at least the rows acked
at the probe's watermark; a 200 linear answer at least every row
acked before the probe began; a 421 is always acceptable.  After the
timeline, the audit phase heals everything and requires every replica
to CONVERGE (serve the exact final per-group counts) and, when a
corruption was scripted, to have COUNTED it (healthz corrupt_frames).

Determinism tier matches the proc plane (README fault matrix): plan
digest + invariant-verdict digest reproduce across runs of one seed;
the history crosses real kernels and is not bit-stable.  The
falsification pair (schedule.py falsification_replica_plan): a
replica booted with --unsafe-serve under a never-healed cut serves
below acked watermarks / past its lease horizon and MUST be caught by
StaleReadNever; the same schedule with the gates on must pass.
"""
from __future__ import annotations

import hashlib
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from raftsql_tpu.chaos.invariants import InvariantViolation
from raftsql_tpu.chaos.schedule import ReplicaChaosPlan

READY_DEADLINE_S = 120.0
CONVERGE_DEADLINE_S = 30.0
PROBE_TIMEOUT_S = 2.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _StreamProxy:
    """A TCP forwarder the nemesis owns: replica -> proxy -> engine
    stream port.  cut() closes every live pipe and makes new ones die
    instantly (a partition as the subscriber sees one: connect may
    succeed, bytes never flow); heal() restores forwarding;
    corrupt_next() flips one bit in the next engine->replica chunk —
    CRC-covered, so exactly one typed corruption surfaces."""

    def __init__(self, upstream_port: int):
        self.upstream_port = upstream_port
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(8)
        self.port = self._sock.getsockname()[1]
        self._mu = threading.Lock()
        self._cut = False                  # raftlint: guarded-by=_mu
        self._corrupt_next = False         # raftlint: guarded-by=_mu
        self._pairs: List[socket.socket] = []  # raftlint: guarded-by=_mu
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True,
                         name="replica-proxy").start()

    def cut(self) -> None:
        with self._mu:
            self._cut = True
            pairs, self._pairs = self._pairs, []
        for s in pairs:
            _sever(s)

    def heal(self) -> None:
        with self._mu:
            self._cut = False

    def corrupt_next(self) -> None:
        with self._mu:
            self._corrupt_next = True

    def stop(self) -> None:
        self._stop.set()
        self.cut()
        _sever(self._sock)

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._mu:
                if self._cut:
                    _sever(conn)
                    continue
            try:
                up = socket.create_connection(
                    ("127.0.0.1", self.upstream_port), timeout=5)
            except OSError:
                _sever(conn)
                continue
            with self._mu:
                self._pairs += [conn, up]
            threading.Thread(target=self._pump, args=(conn, up, False),
                             daemon=True).start()
            threading.Thread(target=self._pump, args=(up, conn, True),
                             daemon=True).start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              downstream: bool) -> None:
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                if downstream:
                    with self._mu:
                        flip = self._corrupt_next
                        if flip:
                            self._corrupt_next = False
                    if flip:
                        b = bytearray(data)
                        b[len(b) // 2] ^= 0x40
                        data = bytes(b)
                dst.sendall(data)
        except OSError:
            pass
        finally:
            _sever(src)
            _sever(dst)


def _sever(sock: socket.socket) -> None:
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _http(port: int, method: str, body: str = "", headers=None,
          path: str = "/", timeout: float = PROBE_TIMEOUT_S):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body.encode() or None,
                     headers=headers or {})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read().decode()
    finally:
        conn.close()


class ReplicaChaosRunner:
    """One seeded run: engine + proxies + replica processes, the
    single-threaded writer/fault/probe loop, then the audit."""

    def __init__(self, plan: ReplicaChaosPlan, workdir: str):
        self.plan = plan
        self.workdir = str(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self.env = dict(
            os.environ, JAX_PLATFORMS="cpu",
            PYTHONPATH=repo_root + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""))
        self.api_port = _free_port()
        self.stream_port = _free_port()
        self.engine: Optional[subprocess.Popen] = None
        self.proxies: List[_StreamProxy] = []
        self.http_ports: List[int] = []
        self.replicas: List[Optional[subprocess.Popen]] = []
        self.acked = [0] * plan.groups       # rows acked per group
        self.wm = [0] * plan.groups          # watermark of last ack
        self.report = {
            "acked": 0, "served_session": 0, "served_linear": 0,
            "refusals": 0, "conn_errors": 0,
            "cuts": 0, "heals": 0, "kills": 0, "restarts": 0,
            "corrupts": 0,
        }
        self.verdicts: Dict[str, str] = {}

    # -- process plumbing ------------------------------------------------

    def _spawn_engine(self) -> None:
        logf = open(os.path.join(self.workdir, "engine.log"), "ab")
        self.engine = subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
             "--port", str(self.api_port),
             "--groups", str(self.plan.groups), "--tick", "0.005",
             "--lease-ticks", "40",
             "--replica-listen", str(self.stream_port)],
            cwd=self.workdir, env=self.env, stdout=logf, stderr=logf)
        logf.close()
        deadline = time.monotonic() + READY_DEADLINE_S
        for g in range(self.plan.groups):
            while True:
                if self.engine.poll() is not None \
                        or time.monotonic() > deadline:
                    raise RuntimeError(
                        "engine not ready: " + self._log_tail("engine"))
                try:
                    st, _h, _b = _http(
                        self.api_port, "PUT",
                        "CREATE TABLE IF NOT EXISTS t (k INTEGER, v TEXT)",
                        headers={"X-Raft-Group": str(g)}, timeout=10)
                    if st in (204, 400):
                        break
                except OSError:
                    pass
                time.sleep(0.3)

    def _spawn_replica(self, i: int) -> None:
        logf = open(os.path.join(self.workdir, f"replica{i}.log"), "ab")
        cmd = [sys.executable, "-m", "raftsql_tpu.replica",
               "--upstream", f"127.0.0.1:{self.proxies[i].port}",
               "--port", str(self.http_ports[i]),
               "--advertise", f"127.0.0.1:{self.http_ports[i]}"]
        if self.plan.unsafe_serve:
            cmd.append("--unsafe-serve")
        self.replicas[i] = subprocess.Popen(
            cmd, cwd=self.workdir, env=self.env,
            stdout=logf, stderr=logf)
        logf.close()

    def _log_tail(self, name: str, nbytes: int = 800) -> str:
        try:
            with open(os.path.join(self.workdir, f"{name}.log"),
                      "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    # -- workload --------------------------------------------------------

    def _write_one(self, n: int) -> None:
        g = n % self.plan.groups
        st, hdrs, body = _http(
            self.api_port, "PUT",
            f"INSERT INTO t VALUES ({n}, 'v{n}')",
            headers={"X-Raft-Group": str(g)}, timeout=15)
        if st != 204:
            raise RuntimeError(f"engine PUT failed: {st} {body[:200]}")
        self.acked[g] += 1
        self.report["acked"] += 1
        wm = hdrs.get("X-Raft-Session")
        if wm:
            self.wm[g] = max(self.wm[g], int(wm))

    def _probe(self, i: int) -> None:
        """One session + one linear probe at replica i, every group.
        StaleReadNever: an ANSWER below the mode's bound is the
        violation; a refusal (421) never is."""
        for g in range(self.plan.groups):
            floor = self.acked[g]            # rows acked at this instant
            for mode, extra in (
                    ("session", {"X-Raft-Session": str(self.wm[g])}),
                    ("linear", {})):
                headers = {"X-Consistency": mode,
                           "X-Raft-Group": str(g), **extra}
                try:
                    st, _h, body = _http(self.http_ports[i], "GET",
                                         "SELECT count(*) FROM t",
                                         headers=headers)
                except OSError:
                    self.report["conn_errors"] += 1
                    continue
                if st == 421:
                    self.report["refusals"] += 1
                    continue
                if st != 200:
                    self.report["conn_errors"] += 1
                    continue
                got = int(body.strip().strip("|"))
                if got < floor:
                    raise InvariantViolation(
                        f"STALE {mode} read at replica {i} group {g}: "
                        f"answered {got} rows with {floor} acked "
                        f"(watermark {self.wm[g]})")
                self.report[f"served_{mode}"] += 1

    def _fire(self, fault) -> None:
        i = fault.target
        if fault.kind == "cut":
            self.proxies[i].cut()
            self.report["cuts"] += 1
        elif fault.kind == "heal":
            self.proxies[i].heal()
            self.report["heals"] += 1
        elif fault.kind == "kill":
            p = self.replicas[i]
            if p is not None and p.poll() is None:
                p.send_signal(signal.SIGKILL)
                p.wait(10)
            self.report["kills"] += 1
        elif fault.kind == "restart":
            self._spawn_replica(i)
            self.report["restarts"] += 1
        elif fault.kind == "corrupt":
            self.proxies[i].corrupt_next()
            self.report["corrupts"] += 1

    def _settle(self) -> None:
        """Before the plan clock starts: every replica attached and
        serving a session read at the current watermark (so the first
        probes measure the ladder, not the bootstrap)."""
        deadline = time.monotonic() + READY_DEADLINE_S
        for i in range(self.plan.replicas):
            while True:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"replica {i} never caught up: "
                        + self._log_tail(f"replica{i}"))
                try:
                    st, _h, _b = _http(
                        self.http_ports[i], "GET",
                        "SELECT count(*) FROM t",
                        headers={"X-Consistency": "session",
                                 "X-Raft-Session": str(self.wm[0]),
                                 "X-Raft-Group": "0"})
                    if st == 200:
                        break
                except OSError:
                    pass
                time.sleep(0.2)

    # -- the run ---------------------------------------------------------

    def run(self) -> dict:
        try:
            self._run_inner()
        except BaseException as e:
            self._flight_dump(e)
            raise
        finally:
            self._teardown()
        return {"plan_digest": self.plan.digest(),
                "result_digest": self._verdict_digest(),
                "seed": self.plan.seed, **self.report}

    def _run_inner(self) -> None:
        plan = self.plan
        self._spawn_engine()
        for i in range(plan.replicas):
            self.proxies.append(_StreamProxy(self.stream_port))
            self.http_ports.append(_free_port())
            self.replicas.append(None)
            self._spawn_replica(i)
        for n in range(plan.groups * 2):     # seed rows + watermarks
            self._write_one(n)
        self._settle()

        faults = sorted(plan.faults, key=lambda f: f.t_ms)
        fi = 0
        n = plan.groups * 2
        t0 = time.monotonic()
        while True:
            t_ms = (time.monotonic() - t0) * 1e3
            if t_ms >= plan.duration_ms:
                break
            while fi < len(faults) and faults[fi].t_ms <= t_ms:
                self._fire(faults[fi])
                fi += 1
            self._write_one(n)
            n += 1
            for i in range(plan.replicas):
                self._probe(i)
            time.sleep(plan.writer_ms / 1e3)
        while fi < len(faults):              # a slow box can't skip one
            self._fire(faults[fi])
            fi += 1
        self.verdicts["stale_read_never"] = "pass"
        self._audit()

    def _audit(self) -> None:
        """Heal everything, then every replica must CONVERGE: serve
        the exact final counts in session mode at the final watermark
        (proving the stream replayed or resynced every gap), and a
        scripted corruption must have been COUNTED by the subscriber
        (healthz corrupt_frames — the CRC caught it)."""
        for proxy in self.proxies:
            proxy.heal()
        deadline = time.monotonic() + CONVERGE_DEADLINE_S
        for i in range(self.plan.replicas):
            for g in range(self.plan.groups):
                while True:
                    if time.monotonic() > deadline:
                        raise InvariantViolation(
                            f"CONVERGENCE: replica {i} group {g} never "
                            f"reached {self.acked[g]} acked rows: "
                            + self._log_tail(f"replica{i}"))
                    try:
                        st, _h, body = _http(
                            self.http_ports[i], "GET",
                            "SELECT count(*) FROM t",
                            headers={"X-Consistency": "session",
                                     "X-Raft-Session": str(self.wm[g]),
                                     "X-Raft-Group": str(g)})
                        if st == 200 \
                                and int(body.strip().strip("|")) \
                                == self.acked[g]:
                            break
                    except OSError:
                        pass
                    time.sleep(0.2)
        self.verdicts["converges"] = "pass"
        if any(f.kind == "corrupt" for f in self.plan.faults):
            target = next(f.target for f in self.plan.faults
                          if f.kind == "corrupt")
            st, _h, body = _http(self.http_ports[target], "GET", "",
                                 path="/healthz")
            doc = json.loads(body)
            if int(doc["replica"].get("corrupt_frames", 0)) < 1:
                raise InvariantViolation(
                    "CORRUPTION: the flipped bit was never surfaced "
                    "as a CRC failure at the subscriber")
            self.verdicts["corruption_detected"] = "pass"

    # -- teardown / flight / digest --------------------------------------

    def _teardown(self) -> None:
        for p in self.replicas:
            if p is not None and p.poll() is None:
                p.terminate()
        if self.engine is not None and self.engine.poll() is None:
            self.engine.terminate()
        for p in [*self.replicas, self.engine]:
            if p is None:
                continue
            try:
                p.wait(timeout=10)
            except Exception:                # noqa: BLE001
                p.kill()
        for proxy in self.proxies:
            proxy.stop()

    def _flight_dump(self, err: BaseException) -> None:
        from raftsql_tpu.obs.flight import FlightRecorder
        bundle: dict = {"plan": self.plan.describe(),
                        "plan_digest": self.plan.digest(),
                        "report": dict(self.report),
                        "acked": list(self.acked),
                        "watermarks": list(self.wm),
                        "logs": {"engine": self._log_tail("engine")}}
        for i in range(len(self.replicas)):
            bundle["logs"][f"replica{i}"] = self._log_tail(f"replica{i}")
        FlightRecorder().dump(
            f"replica-seed{self.plan.seed}", repr(err), meta=bundle)

    def _verdict_digest(self) -> str:
        """What must reproduce across runs of one seed: the plan, the
        invariant verdicts, and which fault kinds fired — booleans,
        because counts beyond the plan's are wall-clock-scheduled."""
        r = self.report
        doc = {
            "plan": self.plan.digest(),
            "invariants": dict(self.verdicts),
            "fired": {k: r[k + "s"] >= sum(
                1 for f in self.plan.faults if f.kind == k)
                for k in ("cut", "heal", "kill", "restart", "corrupt")},
        }
        blob = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]
