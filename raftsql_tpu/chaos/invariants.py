"""Invariant checkers the chaos runners enforce during and after runs.

Four invariants (raft paper §5.4 + the durability contract of
SURVEY.md §2d.8):

  * ELECTION SAFETY — at most one leader per (group, term), across the
    whole run including restarts.
  * COMMIT MONOTONICITY — a peer's durably-observed commit index never
    regresses, including across crash/restart (observations are taken
    only after the tick's fsync barrier, so every observed value is
    durable).
  * LOG MATCHING — survivors agree entry-for-entry (term and payload)
    on the overlap of their committed prefixes.
  * DURABILITY — every entry ever published to the apply plane (i.e.
    acked to a client) reappears, byte-identical, in the post-restart
    replay.

plus a single-register-per-key LINEARIZABILITY check over the KV
plane's completed PUT/GET history.  Violations raise
`InvariantViolation` (an AssertionError so pytest reports them as
failures, not errors).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np


class InvariantViolation(AssertionError):
    pass


class ElectionSafety:
    """At most one leader per (group, term) for the run's lifetime."""

    def __init__(self, leader_code: int = 2):
        self._leader_code = leader_code
        self._leader_of_term: Dict[Tuple[int, int], int] = {}
        self.observations = 0

    def observe(self, tick: int, roles: np.ndarray,
                terms: np.ndarray) -> None:
        """roles/terms are [P, G] snapshots (one peer's row may be
        masked with role -1 for a dead node)."""
        self.observations += 1
        lead_p, lead_g = np.nonzero(roles == self._leader_code)
        for p, g in zip(lead_p.tolist(), lead_g.tolist()):
            key = (g, int(terms[p, g]))
            prev = self._leader_of_term.setdefault(key, p)
            if prev != p:
                raise InvariantViolation(
                    f"t={tick} g={g}: two leaders ({prev}, {p}) "
                    f"in term {key[1]}")


class CommitMonotonic:
    """Durably-observed commit indexes never regress."""

    def __init__(self, peers: int, groups: int):
        self._hi = np.zeros((peers, groups), np.int64)

    def observe(self, tick: int, commits: np.ndarray) -> None:
        if (commits < self._hi).any():
            p, g = np.nonzero(commits < self._hi)
            p, g = int(p[0]), int(g[0])
            raise InvariantViolation(
                f"t={tick} p={p} g={g}: commit regressed "
                f"{self._hi[p, g]} -> {commits[p, g]}")
        np.maximum(self._hi, commits, out=self._hi)


def check_log_matching(tick: int, commits: np.ndarray, plogs) -> None:
    """Survivors' committed prefixes agree entry-for-entry.

    commits: [P, G] committed indexes; plogs: per-peer payload logs
    (storage/log.py — `slice_columns(g, start, n) -> (terms, datas)`).
    Compares every pair's overlap ABOVE both peers' compaction floors:
    compacting scenarios (the compact/InstallSnapshot families) drop
    prefixes at different rates per peer, so the comparable region of a
    pair is (max(floor_a, floor_b), min(commit_a, commit_b)].  Entries
    below a peer's floor were already audited while they were live —
    the floor only ever covers published (committed) entries.
    """
    P, G = commits.shape
    for g in range(G):
        ref_p: Optional[int] = None
        ref_c = 0
        for p in range(P):
            c = int(commits[p, g])
            if c <= 0:
                continue
            if plogs[p].length(g) < c:
                raise InvariantViolation(
                    f"t={tick} p={p} g={g}: payload log shorter than "
                    f"commit ({plogs[p].length(g)} < {c})")
            if ref_p is None:
                ref_p, ref_c = p, c
                continue
            lo = max(plogs[p].start(g), plogs[ref_p].start(g))
            n = min(c, ref_c) - lo
            if n > 0:
                terms, datas = plogs[p].slice_columns(g, lo + 1, n)
                rterms, rdatas = plogs[ref_p].slice_columns(g, lo + 1, n)
                if list(terms) != list(rterms) \
                        or list(datas) != list(rdatas):
                    raise InvariantViolation(
                        f"t={tick} g={g}: committed prefixes diverge "
                        f"between p{ref_p} and p{p}")
            if c > ref_c:
                ref_p, ref_c = p, c


class DurabilityLedger:
    """Every published (client-visible) entry must survive restart."""

    def __init__(self):
        self._committed: Dict[Tuple[int, int], bytes] = {}

    def record(self, group: int, index: int, payload: bytes) -> None:
        prev = self._committed.setdefault((group, index), payload)
        if prev != payload:
            raise InvariantViolation(
                f"g{group} i{index}: committed entry changed content "
                f"({prev!r} -> {payload!r})")

    def __len__(self) -> int:
        return len(self._committed)

    def verify_replay(self, replayed: Dict[Tuple[int, int], bytes],
                      context: str = "",
                      floors: Optional[np.ndarray] = None) -> None:
        """`replayed` maps (group, index) -> payload from the restart's
        replay stream; it must be a superset of everything recorded
        ABOVE the replaying peer's compaction floors (`floors[g]`,
        optional): a compacted prefix legitimately does not replay — its
        entries live on in the state-machine snapshot the compaction was
        gated on, which the runner carries forward separately."""
        for (g, i), payload in self._committed.items():
            if floors is not None and i <= int(floors[g]):
                continue
            got = replayed.get((g, i))
            if got is None:
                raise InvariantViolation(
                    f"{context}: committed entry g{g} i{i} "
                    f"({payload!r}) lost across restart")
            if got != payload:
                raise InvariantViolation(
                    f"{context}: committed entry g{g} i{i} changed "
                    f"across restart ({payload!r} -> {got!r})")


def check_convergence(group: int, survivors: List[Tuple[int, int, Dict]],
                      context: str = "") -> None:
    """CONVERGENCE (post-snapshot survivors): after a fault-free heal
    window, every surviving peer of a group must have applied to the
    SAME index and hold IDENTICAL state-machine state — a peer rebuilt
    through InstallSnapshot included.  This is the end-to-end check the
    per-entry invariants cannot give: an installed snapshot could be
    internally consistent yet wrong (stale applied index, dropped dedup
    window, a key lost in blob serialization) and still pass log
    matching, because the installed peer no longer HAS the log below
    its floor to compare.

    survivors: [(peer, applied_index, state_dict)] for live peers.
    """
    if len(survivors) < 2:
        return
    tops = {a for (_, a, _) in survivors}
    if len(tops) != 1:
        raise InvariantViolation(
            f"{context}: g{group} survivors failed to converge: "
            f"applied indexes "
            f"{sorted((p, a) for (p, a, _) in survivors)}")
    _, _, ref = survivors[0]
    for (p, _, st) in survivors[1:]:
        if st != ref:
            raise InvariantViolation(
                f"{context}: g{group} survivor p{p} state diverges "
                f"from p{survivors[0][0]} at applied "
                f"{survivors[0][1]}")


class RemovedQuorumSafety:
    """NO QUORUM FROM A REMOVED MAJORITY (dynamic membership,
    raftsql_tpu/membership/): every observed leader must be a voter of
    its OWN node's active configuration.  Leadership requires a quorum
    of vote grants; grantors only grant to peers they believe are
    voters (core/step.py voter_src gate) and tallies count only voters
    (mask-weighted quorum) — so once a removal has applied at a
    majority, the removed peers can never again assemble a quorum, and
    a leader observed outside its own config means exactly that
    property broke.  Additionally, once EVERY live node's applied
    config excludes a peer from group g, that peer must never be
    observed leading g at any later tick (covers a stale-config node
    trying to lead on the strength of other removed peers)."""

    def __init__(self, leader_code: int = 2):
        self._leader_code = leader_code
        # (group) -> set of peers fully removed (excluded by every live
        # node's applied config at some earlier observation).
        self._fully_removed: Dict[int, set] = {}
        self.observations = 0

    def observe(self, tick: int, roles: np.ndarray, voter_of,
                live_configs) -> None:
        """roles: [P, G] (dead rows < 0).  voter_of(p, g) -> bool: is p
        a voter (either joint mask) of NODE p's own applied config.
        live_configs: iterable of per-node (voters|joint) bitmask
        getters `fn(g) -> int` for live nodes (used for the
        fully-removed tracking)."""
        self.observations += 1
        P, G = roles.shape
        lead_p, lead_g = np.nonzero(roles == self._leader_code)
        for p, g in zip(lead_p.tolist(), lead_g.tolist()):
            if not voter_of(p, g):
                raise InvariantViolation(
                    f"t={tick} g={g}: peer {p} leads but is not a "
                    f"voter of its own applied configuration")
            if p in self._fully_removed.get(g, ()):
                raise InvariantViolation(
                    f"t={tick} g={g}: REMOVED peer {p} regained "
                    f"leadership — a removed majority formed a quorum")
        fns = list(live_configs)
        if not fns:
            return
        for g in range(G):
            masks = [fn(g) for fn in fns]
            excluded = {p for p in range(P)
                        if all(not (m >> p & 1) for m in masks)}
            if excluded:
                self._fully_removed.setdefault(g, set()).update(excluded)


class SessionConsistency:
    """Read-your-writes / monotonic reads for WATERMARK-carrying reads
    (the session/follower read modes): a read presenting watermark `w`
    on key k must return a committed write to k at log index >= the
    newest committed write to k at-or-below w — i.e. at least as fresh
    as everything the watermark covers.  Weaker than linearizability
    (a session read may legally miss writes committed after w), which
    is exactly why these modes get their own checker instead of the
    register rule.

    The committed write history arrives via note_commit(group, index,
    key, value) from whatever apply stream the runner trusts (unique
    values, like the register checker).  Thread-safe.
    """

    def __init__(self):
        import threading
        self._mu = threading.Lock()
        # key -> sorted-ish list of (global_order, value); value -> ord.
        self._by_key: Dict[Tuple[int, str], List[Tuple[int, str]]] = {}
        self._ord: Dict[str, Tuple[int, int]] = {}  # value -> (g, idx)
        self.reads_checked = 0

    def note_commit(self, group: int, index: int, key: str,
                    value: str) -> None:
        with self._mu:
            self._by_key.setdefault((group, key), []).append(
                (index, value))
            self._ord[value] = (group, index)

    def check_read(self, group: int, key: str, watermark: int,
                   value: str, mode: str = "session") -> None:
        """`value` came back from a read of `key` carrying `watermark`
        (a commit index of `group`)."""
        with self._mu:
            self.reads_checked += 1
            hist = self._by_key.get((group, key), ())
            floor = 0
            floor_val = None
            for (idx, v) in hist:
                if idx <= watermark and idx > floor:
                    floor, floor_val = idx, v
            if floor_val is None:
                return               # watermark predates every write
            if value == "":
                raise InvariantViolation(
                    f"{mode} read(g{group} {key!r}, wm={watermark}) "
                    f"returned the initial value but {floor_val!r} "
                    f"committed at index {floor} <= wm")
            got = self._ord.get(value)
            if got is None or got[0] != group:
                raise InvariantViolation(
                    f"{mode} read(g{group} {key!r}) returned a value "
                    f"never committed to that key: {value!r}")
            if got[1] < floor:
                raise InvariantViolation(
                    f"{mode} read(g{group} {key!r}, wm={watermark}) "
                    f"returned STALE {value!r} (index {got[1]}) — "
                    f"{floor_val!r} committed at {floor} <= wm")


class RegisterLinearizability:
    """Per-key register linearizability over completed PUT/GET history.

    PUT values are globally unique (the runners guarantee it), so a
    read names exactly the write it observed and the real-time
    precedence check is direct — no state-space search:

      a GET returning write w's value is legal iff
        * w was invoked before the GET's response (no reading the
          future), and
        * w does not STRICTLY PRECEDE (w.resp <= w2.inv) any write w2
          on the key that completed before the GET was invoked — such
          a w2 must linearize after w and before the GET, making w
          stale.

    The initial value "" is legal only while no write on the key has
    completed before the GET's invocation.  Incomplete writes (e.g.
    proposals lost in a crash, which may still commit after a restart)
    may linearize anywhere after their invocation or never — exactly
    the window these rules grant.  Overlapping writes to one key may
    legally complete in either order (leader failover reorders
    re-routed proposal queues), which is why precedence, not issue
    order, is the test.  This is the standard necessary-condition
    per-op check (cf. Jepsen's register checkers); it does not search
    for a single total order across reads.
    """

    def __init__(self):
        import threading
        # One lock serializes the logical clock and every history
        # mutation: the process-plane read nemesis drives this checker
        # from concurrent client threads, where an unlocked clock
        # could order two racing ops identically and mask (or invent)
        # a precedence edge.  Single-threaded runners pay one
        # uncontended acquire per op.
        self._mu = threading.Lock()
        self._clock = 0
        self._writes: Dict[str, list] = {}   # value -> [key, inv, resp]
        # key -> [(inv, resp), ...] of COMPLETED writes.
        self._completed: Dict[str, List[Tuple[int, int]]] = {}
        self.reads_checked = 0
        # Per read MODE accounting (lease/read_index/session/follower/
        # linear/...): the nemesis report proves every family actually
        # exercised the invariant.
        self.reads_by_mode: Dict[str, int] = {}

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    # -- write lifecycle -----------------------------------------------

    def begin_write(self, key: str, value: str) -> None:
        with self._mu:
            if value in self._writes:
                raise ValueError(
                    f"write values must be unique: {value!r}")
            self._writes[value] = [key, self._tick(), None]

    def end_write(self, value: str) -> None:
        with self._mu:
            w = self._writes.get(value)
            if w is None or w[2] is not None:
                return                   # unknown or already completed
            w[2] = self._tick()
            self._completed.setdefault(w[0], []).append((w[1], w[2]))

    # -- read lifecycle ------------------------------------------------

    def begin_read(self, key: str, mode: str = "linear"
                   ) -> Tuple[str, int, str]:
        with self._mu:
            return key, self._tick(), mode

    def end_read(self, handle, value: str) -> None:
        key, inv, mode = (handle if len(handle) == 3
                          else (*handle, "linear"))
        with self._mu:
            resp = self._tick()
            self.reads_checked += 1
            self.reads_by_mode[mode] = self.reads_by_mode.get(mode,
                                                              0) + 1
            completed = self._completed.get(key, ())
            if value == "":
                for (i2, r2) in completed:
                    if r2 <= inv:
                        raise InvariantViolation(
                            f"{mode} read({key!r}) returned the "
                            f"initial value after a write completed "
                            f"before it")
                return
            w = self._writes.get(value)
            if w is None or w[0] != key:
                raise InvariantViolation(
                    f"{mode} read({key!r}) returned a value never "
                    f"written to that key: {value!r}")
            _, w_inv, w_resp = w
            if w_inv > resp:
                raise InvariantViolation(
                    f"{mode} read({key!r}) returned {value!r} invoked "
                    f"after the read's response")
            if w_resp is not None:
                for (i2, r2) in completed:
                    if r2 <= inv and w_resp <= i2:
                        raise InvariantViolation(
                            f"{mode} read({key!r}) returned STALE "
                            f"value {value!r}: a later write "
                            f"completed before the read began")


class TransferAvailability:
    """No availability loss during graceful leadership transfer
    (PR 11).  Every violation message carries the TRANSFER-AVAILABILITY
    token so the falsification harness can match on it precisely.

    Checks, per issued transfer:
      * the latch RESOLVES (completed or aborted) within the engine
        deadline plus a two-election-cycle settling margin — a stuck
        latch is a permanently closed group;
      * `must_complete` transfers (the directed falsification probe)
        must end `completed` with stall <= max_stall_ticks — the
        broken unsafe kernel deterministically ABORTS here because the
        behind target cannot win the election it was handed;
      * a transfer resolving in fault-free air must be followed by a
        committed probe write within probe_ticks (aborted transfers
        leave the group SERVING, not just unlatched).

    The runner feeds outcomes from the host's transfer event log and
    calls check(t) every tick; crashes wipe pending state (the latch
    dies with the process — a transfer outstanding at crash time is
    void, not violated)."""

    def __init__(self, election_ticks: int, deadline_ticks: int,
                 max_stall_ticks: int, probe_ticks: int):
        self.election_ticks = election_ticks
        self.deadline_ticks = deadline_ticks
        self.max_stall_ticks = max_stall_ticks
        self.probe_ticks = probe_ticks
        # group -> (issue_tick, must_complete)
        self._pending: Dict[int, Tuple[int, bool]] = {}
        # probe value -> (deadline_tick, group)
        self._probes: Dict[str, Tuple[int, int]] = {}
        self.completed = 0
        self.aborted = 0
        self.max_stall = 0
        self.probes_confirmed = 0

    # -- transfer lifecycle --------------------------------------------

    def note_issued(self, tick: int, group: int,
                    must_complete: bool) -> None:
        self._pending[group] = (tick, must_complete)

    def note_outcome(self, tick: int, group: int, outcome: str,
                     stall_ticks: int) -> None:
        issued = self._pending.pop(group, None)
        self.max_stall = max(self.max_stall, int(stall_ticks))
        if outcome == "completed":
            self.completed += 1
        else:
            self.aborted += 1
        if issued is None:
            return
        _t0, must = issued
        if must and outcome != "completed":
            raise InvariantViolation(
                f"TRANSFER-AVAILABILITY: directed transfer of group "
                f"{group} was required to complete but ended "
                f"{outcome!r} after {stall_ticks} ticks — the engine "
                f"deposed a leader without getting its successor "
                f"elected")
        if must and stall_ticks > self.max_stall_ticks:
            raise InvariantViolation(
                f"TRANSFER-AVAILABILITY: directed transfer of group "
                f"{group} stalled proposals for {stall_ticks} ticks "
                f"(bound {self.max_stall_ticks})")

    def note_crash(self) -> None:
        # Latches (and any not-yet-committed probe) die with the
        # process; outstanding transfers are void, not violated.
        self._pending.clear()
        self._probes.clear()

    # -- serving probes ------------------------------------------------

    def arm_probe(self, tick: int, group: int, value: str) -> None:
        self._probes[value] = (tick + self.probe_ticks, group)

    def probe_committed(self, value: str) -> None:
        if self._probes.pop(value, None) is not None:
            self.probes_confirmed += 1

    # -- per-tick / end-of-run checks ----------------------------------

    def check(self, tick: int) -> None:
        limit = self.deadline_ticks + 2 * self.election_ticks
        for group, (t0, _must) in self._pending.items():
            if tick - t0 > limit:
                raise InvariantViolation(
                    f"TRANSFER-AVAILABILITY: transfer of group {group} "
                    f"issued at tick {t0} still unresolved at tick "
                    f"{tick} (engine deadline {self.deadline_ticks})")
        for value, (dl, group) in self._probes.items():
            if tick > dl:
                raise InvariantViolation(
                    f"TRANSFER-AVAILABILITY: post-transfer probe write "
                    f"{value!r} on group {group} did not commit within "
                    f"{self.probe_ticks} ticks — the group stopped "
                    f"serving after its transfer resolved")

    def final_check(self, tick: int) -> None:
        for group, (t0, _must) in self._pending.items():
            raise InvariantViolation(
                f"TRANSFER-AVAILABILITY: transfer of group {group} "
                f"issued at tick {t0} never resolved by end of run "
                f"({tick} ticks)")


class NoAckedWriteLost:
    """Elastic-keyspace safety (PR 16): every acked write stays readable
    in EXACTLY ONE post-reshard group.  Every violation message carries
    the NO-ACKED-WRITE-LOST token so the falsification harness can
    match on it precisely.

    The runner feeds it the client ack stream (`note_ack` fires when
    peer 0 applies a keyed write that was not bounced by the reshard
    fence) and asks for two checks:

      * `check_moved` at the instant a router flip lands: the moved
        keys' latest acked values must already be served by the NEW
        owner — a coordinator that flipped before the destination
        durably applied the copies fails here (the broken_flip
        falsification variant);
      * `check_exclusive` at verb completion and after every restart
        with no verb in flight (the WAL-fold post-mortem: the runner's
        keyed state was just rebuilt from the replayed logs): each
        acked key's latest value is served by its owner and by NO other
        group — a half-cleaned source or a half-copied destination
        fails here.
    """

    def __init__(self):
        self.acked: Dict[str, str] = {}    # key -> latest acked value
        self.moved_checks = 0
        self.exclusive_checks = 0

    def note_ack(self, key: str, value: str) -> None:
        self.acked[key] = value

    def check_moved(self, moved_keys, dst: int, dst_kv: Dict[str, str],
                    context: str = "") -> None:
        for k in sorted(moved_keys):
            want = self.acked.get(k)
            if want is None:
                continue                   # never acked: nothing owed
            got = dst_kv.get(k)
            self.moved_checks += 1
            if got != want:
                raise InvariantViolation(
                    f"NO-ACKED-WRITE-LOST: router flipped key {k!r} to "
                    f"group {dst} but the acked value {want!r} is not "
                    f"there (new owner serves {got!r}) — the flip "
                    f"outran the copy fence{context}")

    def check_exclusive(self, keymap, gkvs: Dict[int, Dict[str, str]],
                        context: str = "") -> None:
        for k in sorted(self.acked):
            want = self.acked[k]
            owner = keymap.group_of(k)
            got = gkvs.get(owner, {}).get(k)
            self.exclusive_checks += 1
            if got != want:
                raise InvariantViolation(
                    f"NO-ACKED-WRITE-LOST: acked key {k!r}={want!r} not "
                    f"served by its owner group {owner} (serves "
                    f"{got!r}){context}")
            for g, kv in gkvs.items():
                if g != owner and k in kv:
                    raise InvariantViolation(
                        f"NO-ACKED-WRITE-LOST: key {k!r} readable in "
                        f"group {g} AND its owner {owner} — reshard "
                        f"cleanup left a duplicate shard{context}")


class NoAvailabilityLoss:
    """Elastic-keyspace availability (PR 16): resharding one key range
    never takes the REST of the keyspace down, and verbs always
    resolve.  Every violation message carries the NO-AVAILABILITY-LOSS
    token.

    Probe writes to keys outside the moving range, armed only in
    fault-free air while a verb is active, must commit within
    `probe_ticks`.  A verb unresolved `verb_deadline_ticks` after issue
    (or still in flight at end of run) is a violation — a wedged
    coordinator is a permanently frozen key range.  Crashes void armed
    probes (the client died with the process) and restart the active
    verb's clock (recovery legitimately takes time)."""

    def __init__(self, probe_ticks: int, verb_deadline_ticks: int):
        self.probe_ticks = probe_ticks
        self.verb_deadline_ticks = verb_deadline_ticks
        self._probes: Dict[str, Tuple[int, str]] = {}
        self._verb: Optional[Tuple[int, int]] = None  # (issue_tick, id)
        self.probes_confirmed = 0

    # -- verb lifecycle ------------------------------------------------
    def verb_started(self, tick: int, vid: int) -> None:
        self._verb = (tick, vid)

    def verb_resolved(self) -> None:
        self._verb = None

    def note_crash(self, tick: int) -> None:
        self._probes.clear()
        if self._verb is not None:
            self._verb = (tick, self._verb[1])

    # -- probes --------------------------------------------------------
    def arm_probe(self, tick: int, key: str, value: str) -> None:
        self._probes[value] = (tick + self.probe_ticks, key)

    def probe_committed(self, value: str) -> None:
        if self._probes.pop(value, None) is not None:
            self.probes_confirmed += 1

    # -- per-tick / end-of-run checks ----------------------------------
    def check(self, tick: int) -> None:
        for value, (dl, key) in self._probes.items():
            if tick > dl:
                raise InvariantViolation(
                    f"NO-AVAILABILITY-LOSS: probe write {value!r} to "
                    f"key {key!r} (outside the moving range) did not "
                    f"commit within {self.probe_ticks} ticks of a "
                    f"reshard verb — the verb took the rest of the "
                    f"keyspace down with it")
        if self._verb is not None:
            t0, vid = self._verb
            if tick - t0 > self.verb_deadline_ticks:
                raise InvariantViolation(
                    f"NO-AVAILABILITY-LOSS: reshard verb {vid} issued "
                    f"at tick {t0} still unresolved at tick {tick} "
                    f"(bound {self.verb_deadline_ticks}) — its key "
                    f"range is frozen indefinitely")

    def final_check(self, tick: int) -> None:
        if self._verb is not None:
            t0, vid = self._verb
            raise InvariantViolation(
                f"NO-AVAILABILITY-LOSS: reshard verb {vid} issued at "
                f"tick {t0} never resolved by end of run ({tick} "
                f"ticks)")
