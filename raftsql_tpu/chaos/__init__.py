"""Deterministic chaos harness: seeded fault schedules, crash/partition/
disk-fault injection, and invariant checking over the live engine.

The reference's only fault story is test-driven node stop/restart
(reference raftsql_test.go:47-52, 117-170).  This package is the
systematic version, built on three seams the engine already exposes:

  * the dense message plane (transport/faults.py masks) for seeded
    drops, delays, and partitions — applied between device dispatches;
  * the storage I/O seam (storage/fsio.py) for failed fsyncs, torn
    writes, and unsynced-tail loss at a chosen operation count;
  * hard process-crash simulation (open durable fds redirected to
    /dev/null so buffered bytes can never be resurrected by a flush)
    plus full restart-from-WAL, for both the fused single-dispatch
    runtime and the threaded/lockstep RaftNode cluster.

Every scenario is a tick-indexed `ChaosSchedule` derived from ONE seed;
re-running a seed reproduces the identical schedule (digest-checked by
`make chaos`).  After (and during) every scenario the invariants are
enforced (chaos/invariants.py): committed-entry durability across
crashes, at most one leader per term, log matching across survivors,
linearizability of the KV plane's completed PUT/GET history, commit
monotonicity — plus, for the InstallSnapshot families, post-snapshot
survivor convergence.  `make chaos-matrix` sweeps one seed through
every scenario FAMILY (asymmetric partitions, per-peer clock skew,
wire-frame corruption, ENOSPC, fsync stalls, compaction and
InstallSnapshot crash interleavings, and the real TCP transport) —
see the README's fault-matrix table.

The PROCESS plane (chaos/proc.py, `make chaos-procs`) goes one level
further down: a seeded nemesis over real `server/main.py` OS processes
— SIGKILL, SIGSTOP/SIGCONT stalls, rolling-restart storms, and
env-injected disk faults (RAFTSQL_FSIO_FAULTS) — under a live
acked-PUT workload through the hardened api/client.py.  Its schedule
and invariant VERDICTS are seed-deterministic; its committed history
crosses real kernel scheduling and is not (README "Process-plane
chaos").
"""
from raftsql_tpu.chaos.invariants import (DurabilityLedger, ElectionSafety,
                                          InvariantViolation,
                                          RegisterLinearizability,
                                          RemovedQuorumSafety,
                                          check_convergence)
from raftsql_tpu.chaos.schedule import (LEADER_TARGET, AsymPartitionWindow,
                                        ChaosSchedule, CorruptWindow,
                                        CrashEvent, DelayWindow, DropWindow,
                                        EnospcFault, FsyncFault, FsyncStall,
                                        MemberEvent, MembershipChaosPlan,
                                        NodeBoot, NodeChaosPlan, NodeCrash,
                                        PartitionWindow, ProcChaosPlan,
                                        ProcFsioSpec, ProcKill,
                                        ProcRestartStorm, ProcStall,
                                        SkewWindow,
                                        TcpChaosPlan, TcpRebindPlan,
                                        TornWriteFault, TransferEvent,
                                        TransferNemesisPlan,
                                        falsification_transfer_plan,
                                        generate, generate_asym,
                                        generate_compact,
                                        generate_corrupt_plan,
                                        generate_enospc,
                                        generate_membership_plan,
                                        generate_node_plan,
                                        generate_skew,
                                        generate_snapshot_plan,
                                        generate_procs,
                                        generate_stall, generate_tcp_plan,
                                        generate_tcp_rebind_plan,
                                        generate_transfers)
from raftsql_tpu.chaos.proc import (ProcChaosRunner, ProcCluster,
                                    ProcTransferChaosRunner)
from raftsql_tpu.chaos.scenarios import (FusedChaosRunner,
                                         MembershipChaosRunner,
                                         NodeClusterChaosRunner,
                                         SnapshotChaosRunner,
                                         TcpClusterChaosRunner,
                                         TcpRebindChaosRunner,
                                         TransferChaosRunner)

__all__ = [
    "LEADER_TARGET", "AsymPartitionWindow", "ChaosSchedule",
    "CorruptWindow", "CrashEvent", "DelayWindow", "DropWindow",
    "EnospcFault", "FsyncFault", "FsyncStall", "MemberEvent",
    "MembershipChaosPlan", "NodeBoot", "NodeChaosPlan",
    "NodeCrash", "PartitionWindow", "ProcChaosPlan", "ProcChaosRunner",
    "ProcCluster", "ProcFsioSpec", "ProcKill", "ProcRestartStorm",
    "ProcStall", "SkewWindow", "TcpChaosPlan",
    "TcpRebindPlan", "TornWriteFault", "generate", "generate_asym",
    "generate_compact", "generate_corrupt_plan", "generate_enospc",
    "generate_membership_plan", "generate_node_plan", "generate_procs",
    "generate_skew", "generate_snapshot_plan", "generate_stall",
    "generate_tcp_plan", "generate_tcp_rebind_plan",
    "generate_transfers", "falsification_transfer_plan",
    "TransferEvent", "TransferNemesisPlan", "TransferChaosRunner",
    "ProcTransferChaosRunner",
    "DurabilityLedger", "ElectionSafety", "InvariantViolation",
    "RegisterLinearizability", "RemovedQuorumSafety",
    "check_convergence", "FusedChaosRunner", "MembershipChaosRunner",
    "NodeClusterChaosRunner", "SnapshotChaosRunner",
    "TcpClusterChaosRunner", "TcpRebindChaosRunner",
]
