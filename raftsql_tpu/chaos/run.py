"""`make chaos` entry point: run a seeded chaos scenario and prove it
reproduces.

    python -m raftsql_tpu.chaos.run --seed 0 --ticks 240 --runs 2

Generates the seed's ChaosSchedule (>= 2 partitions, >= 2 crash/restart
events, >= 1 injected fsync fault, plus a torn-write power loss), runs
it against a fresh FusedClusterNode data dir per run, and prints one
JSON line per run.  With --runs > 1 the runs must produce IDENTICAL
schedule and result digests — determinism is an asserted property, not
a hope.  Exit code 0 only when every run passed all four invariants
(durability, single leader per term, log matching, KV linearizability
— violations raise and exit 1), the digests agree, and at least one
storage fault actually fired.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("SEED", "0")))
    ap.add_argument("--ticks", type=int, default=240)
    ap.add_argument("--runs", type=int, default=2,
                    help="repeat the seed and require identical digests")
    ap.add_argument("--steps", type=int, default=1,
                    help="fused steps per dispatch (epoch-framed when >1)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from raftsql_tpu.chaos.schedule import generate
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner

    sched = generate(args.seed, ticks=args.ticks)
    reports = []
    for run in range(args.runs):
        with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
            r = FusedChaosRunner(sched, d, steps=args.steps).run()
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
    ok = True
    if not all(r["fsync_faults"] >= 1 and r["torn_writes"] >= 1
               for r in reports):
        print("CHAOS FAIL: a scheduled storage fault never fired",
              file=sys.stderr)
        ok = False
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    if len(digests) != 1:
        print(f"CHAOS FAIL: non-deterministic run: {digests}",
              file=sys.stderr)
        ok = False
    if ok:
        print(f"chaos ok: seed={args.seed} ticks={args.ticks} "
              f"schedule={reports[0]['schedule_digest']} "
              f"result={reports[0]['result_digest']} "
              f"(x{args.runs} identical)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
