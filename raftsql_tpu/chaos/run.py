"""`make chaos` / `make chaos-matrix` entry points: run seeded chaos
scenarios and prove they reproduce.

    python -m raftsql_tpu.chaos.run --seed 0 --ticks 240 --runs 2
    python -m raftsql_tpu.chaos.run --matrix --seed 0
    python -m raftsql_tpu.chaos.run --family enospc --seed 3
    python -m raftsql_tpu.chaos.run --procs --seed 0
    python -m raftsql_tpu.chaos.run --pod --seed 0
    python -m raftsql_tpu.chaos.run --replica --seed 0

Default mode generates the seed's full ChaosSchedule (>= 2 partitions,
>= 2 crash/restart events, >= 1 injected fsync fault, plus a torn-write
power loss), runs it against a fresh FusedClusterNode data dir per run,
and prints one JSON line per run.  With --runs > 1 the runs must produce
IDENTICAL schedule and result digests — determinism is an asserted
property, not a hope.

--matrix sweeps ONE seed through every scenario FAMILY of the fault
matrix (ROADMAP open items → chaos/schedule.py generators):

    asym             one-directional partitions        (fused plane)
    skew             per-peer clock skew               (fused plane)
    mesh_skew        per-peer clock skew on the MESH runtime
                     (groups-sharded shard_map step + per-shard WALs;
                     needs a multi-device platform — the Makefile
                     targets force 8 virtual CPU devices)
    corrupt          wire-frame corruption             (lockstep wire plane)
    enospc           disk-full on WAL append           (fused plane)
    fsync_stall      slow-disk fsync latency           (fused plane)
    compact          compaction + crash interleaving   (fused plane)
    snapshot         compaction + InstallSnapshot + crash (lockstep plane)
    tcp              drops/corruption/asym/delays      (REAL TCP transport)
    membership       add/promote/remove churn + node replacement under
                     faults (lockstep plane, raftsql_tpu/membership/)
    tcp_rebind       crash/restart with port rebinding (REAL TCP transport)

--procs is the PROCESS plane (`make chaos-procs`): a seeded nemesis
over real `server/main.py` OS processes — SIGKILL (leader-targeted and
random), SIGSTOP/SIGCONT stalls, a rolling-restart storm, and
env-injected disk faults (RAFTSQL_FSIO_FAULTS: ENOSPC + a hard process
exit at a WAL fsync) — under a live acked-PUT workload.  The seed runs
twice; schedule and VERDICT digests must match (the committed history
crosses real kernel scheduling and is not bit-reproducible — the
weakest determinism tier, like `tcp`).

Every family except `tcp` is run twice and must reproduce identical
schedule + result digests.  The TCP family crosses real kernel sockets,
so arrival interleaving is not virtualizable: its SCHEDULE digest is
deterministic and its invariants must hold, but the committed history
is not bit-reproducible (documented in the README fault matrix) — it
runs once.  Exit code 0 only when every family passed every invariant
(violations raise), every deterministic family reproduced, and each
family's signature faults actually fired.

--pod is the MULTI-HOST POD plane (`make chaos-pod`): a seeded
nemesis over a real 2-process pod (raftsql_tpu/pod/ — host processes
lockstepped by the TcpPodTransport collective, each durable for its
own group shards): a propose-plane cut, SIGKILL of the
non-coordinator host, SIGKILL of the coordinator, then a fault-free
audit incarnation whose merged cross-host replay must hold every
acked write exactly once on every host — plus the premature-ack
falsification pair.  Proc-plane determinism tier (plan + verdict
digests reproduce; committed history does not).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _run_fused(sched, steps: int = 1) -> dict:
    from raftsql_tpu.chaos.scenarios import FusedChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return FusedChaosRunner(sched, d, steps=steps).run()


def _run_mesh(sched) -> dict:
    from raftsql_tpu.chaos.scenarios import MeshChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return MeshChaosRunner(sched, d).run()


def _run_reads(plan) -> dict:
    from raftsql_tpu.chaos.scenarios import ReadNemesisRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return ReadNemesisRunner(plan, d).run()


def _run_quorum(plan) -> dict:
    from raftsql_tpu.chaos.scenarios import QuorumChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return QuorumChaosRunner(plan, d).run()


def _run_transfers(plan) -> dict:
    from raftsql_tpu.chaos.scenarios import TransferChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return TransferChaosRunner(plan, d).run()


def _check(ok: bool, msg: str) -> bool:
    if not ok:
        print(f"CHAOS FAIL: {msg}", file=sys.stderr)
    return ok


# family -> (runner, deterministic, fired_predicate)
def _family_specs():
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.scenarios import (MembershipChaosRunner,
                                             NodeClusterChaosRunner,
                                             SnapshotChaosRunner,
                                             TcpClusterChaosRunner,
                                             TcpRebindChaosRunner)

    def node_run(runner_cls, plan):
        with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
            return runner_cls(plan, d).run()

    return {
        "asym": (lambda seed: _run_fused(S.generate_asym(seed)), True,
                 lambda r: r["asym_partitions"] >= 2),
        "skew": (lambda seed: _run_fused(S.generate_skew(seed)), True,
                 lambda r: r["skew_ticks"] > 0),
        "mesh_skew": (lambda seed: _run_mesh(S.generate_skew(seed)),
                      True, lambda r: r["skew_ticks"] > 0
                      and r["crashes"] >= 1),
        "corrupt": (lambda seed: node_run(NodeClusterChaosRunner,
                                          S.generate_corrupt_plan(seed)),
                    True, lambda r: r["corrupt_frames"] > 0),
        "enospc": (lambda seed: _run_fused(S.generate_enospc(seed)), True,
                   lambda r: r["enospc_hits"] >= 2),
        "fsync_stall": (lambda seed: _run_fused(S.generate_stall(seed)),
                        True, lambda r: r["fsync_stalls"] > 0),
        "compact": (lambda seed: _run_fused(S.generate_compact(seed)),
                    True, lambda r: r["compactions"] > 0
                    and r["crashes"] >= 2),
        "snapshot": (lambda seed: node_run(SnapshotChaosRunner,
                                           S.generate_snapshot_plan(seed)),
                     True, lambda r: r["snapshots_installed"] > 0
                     and r["compactions"] > 0 and r["crashes"] >= 2),
        "tcp": (lambda seed: node_run(TcpClusterChaosRunner,
                                      S.generate_tcp_plan(seed)),
                False, lambda r: r["corrupt_frames_dropped"] > 0
                and r["commits"] > 20),
        "membership": (lambda seed: node_run(
                           MembershipChaosRunner,
                           S.generate_membership_plan(seed)),
                       True, lambda r: r["member_ops_applied"]
                       >= 2 * 3 and r["boots"] >= 1
                       and r["crashes"] >= 2 and r["commits"] > 20),
        "tcp_rebind": (lambda seed: node_run(
                           TcpRebindChaosRunner,
                           S.generate_tcp_rebind_plan(seed)),
                       False, lambda r: r["rebinds"] == 2
                       and r["commits"] > 20),
        "reads": (lambda seed: _run_reads(S.generate_reads(seed)),
                  True, lambda r: r["lease_reads"] > 0
                  and r["session_reads"] > 0
                  and r["follower_reads"] > 0
                  and r["reads_by_mode"].get("linear", 0) > 0
                  and r["skew_ticks"] > 0 and r["crashes"] >= 1),
        "quorum": (lambda seed: _run_quorum(S.generate_quorum(seed)),
                   True, lambda r: r["witness_appends"] > 0
                   and r["witness_publishes"] == 0
                   and r["apply_streams"] == r["wal_streams"] - 1
                   and r["lease_reads"] > 0 and r["crashes"] >= 1
                   and r["partitions"] >= 1),
        "transfers": (lambda seed: _run_transfers(
                          S.generate_transfers(seed)),
                      True, lambda r: r["transfers_requested"] >= 6
                      and r["transfers_completed"] >= 1
                      and r["transfer_probes_confirmed"] >= 1
                      and r["partitions"] >= 1 and r["crashes"] >= 1),
    }


def _digests(r: dict):
    return (r.get("schedule_digest") or r.get("plan_digest"),
            r.get("result_digest"))


def run_procs(seed: int, ticks: int, runs: int = 2) -> int:
    """Process-plane chaos: run the seed `runs` times over fresh work
    dirs; every run must pass every invariant (violations raise), every
    scripted fault family must fire, and all runs must agree on
    schedule + verdict digests."""
    from raftsql_tpu.chaos.proc import ProcChaosRunner
    from raftsql_tpu.chaos.schedule import generate_procs

    plan = generate_procs(seed, ticks=ticks)
    reports = []
    for run in range(runs):
        with tempfile.TemporaryDirectory(prefix="raftsql-procs-") as d:
            r = ProcChaosRunner(plan, d).run()
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
    ok = True
    for r in reports:
        ok &= _check(
            r["kills"] >= len(plan.kills) and r["stalls"]
            >= len(plan.stalls)
            and r["storm_restarts"] >= plan.peers * len(plan.storms)
            and r["fsio_exits"] >= 1 and r["fatal_exits"] >= 1,
            f"procs: a scripted fault family never fired ({r})")
        ok &= _check(r["unexpected_exits"] == 0,
                     f"procs: a server died of something unscripted "
                     f"({r})")
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1,
                 f"procs: non-reproducible verdicts: {digests}")
    if ok:
        print(f"chaos procs ok: seed={seed} "
              f"schedule={reports[0]['schedule_digest']} "
              f"verdict={reports[0]['result_digest']} (x{runs} "
              f"identical)")
    return 0 if ok else 1


def run_reads(seed: int, runs: int = 2,
              with_procs: bool = True) -> int:
    """`make chaos-reads`: the full read-plane gauntlet.

    1. The fused read nemesis (family `reads`), run twice — schedule +
       result digests must reproduce, every read mode must fire, and
       the read-linearizability / session invariants must hold.
    2. The FALSIFICATION pair (schedule.py falsification_plan): the
       deliberately mis-sized lease bound under 4x skew MUST be caught
       by the register invariant as a stale lease read, and the SAME
       schedule with a correctly sized bound must pass — proving the
       harness detects exactly the bound, not chaos in general.
    3. The process-plane read nemesis (chaos/proc.py
       ProcReadChaosRunner): linear/session/follower HTTP reads race
       the nemesis over real server processes; verdict digests must
       reproduce.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    reports = []
    for run in range(runs):
        r = _run_reads(S.generate_reads(seed))
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
    fired = _family_specs()["reads"][2]
    for r in reports:
        ok &= _check(fired(r),
                     f"reads: a read family never fired ({r})")
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1,
                 f"reads: non-reproducible: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_reads(S.falsification_plan(seed, broken=True))
            except InvariantViolation as e:
                caught = "STALE" in str(e) or "stale" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the BROKEN lease bound was "
                         "NOT caught by the read invariant")
    try:
        r = _run_reads(S.falsification_plan(seed, broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the CORRECT bound "
                           f"tripped the invariant: {e}")
    else:
        ok &= _check(r["lease_reads"] > 0,
                     "falsification control: no lease reads granted")
        print(json.dumps({"falsification_control": "passed",
                          "lease_reads": r["lease_reads"]}))

    if with_procs:
        from raftsql_tpu.chaos.proc import ProcReadChaosRunner
        plan = S.generate_procs(seed, ticks=60)
        preports = []
        for run in range(runs):
            with tempfile.TemporaryDirectory(
                    prefix="raftsql-reads-procs-") as d:
                r = ProcReadChaosRunner(plan, d).run()
            r["run"] = run
            preports.append(r)
            print(json.dumps(r, sort_keys=True))
        for r in preports:
            ok &= _check(r["linear_reads"] > 0
                         and r["session_reads"] > 0
                         and r["follower_reads"] > 0,
                         f"reads-procs: a read family never fired "
                         f"({r})")
            ok &= _check(r["unexpected_exits"] == 0,
                         f"reads-procs: unscripted server death ({r})")
        pdig = {(r["schedule_digest"], r["result_digest"])
                for r in preports}
        ok &= _check(len(pdig) == 1,
                     f"reads-procs: non-reproducible verdicts: {pdig}")
    if ok:
        print(f"chaos reads ok: seed={seed} "
              f"schedule={reports[0]['schedule_digest']} "
              f"result={reports[0]['result_digest']} "
              f"falsification=caught")
    return 0 if ok else 1


def run_transfers(seed: int, runs: int = 2,
                  with_procs: bool = True) -> int:
    """`make chaos-transfer`: the leadership-transfer gauntlet.

    1. The fused transfer nemesis (family `transfers`), run twice —
       graceful transfers race drops, leader-targeted partitions, asym
       cuts, skew and crash+restart under acked-PUT load; schedule +
       result digests must reproduce and the TransferAvailability /
       election-safety / durability invariants must hold.
    2. The FALSIFICATION pair (schedule.py falsification_transfer_plan):
       the deliberately broken kernel (unsafe_transfer — abdicate
       before the target caught up, the thesis-§3.10 mistake) MUST be
       caught by TransferAvailability on a directed lagging-target
       schedule, and the SAME schedule with the correct kernel must
       pass with the transfer completed — proving the harness detects
       exactly the broken handoff, not chaos in general.
    3. The process-plane transfer nemesis (chaos/proc.py
       ProcTransferChaosRunner): POST /transfer against real server
       processes under the seeded nemesis script; verdict digests must
       reproduce.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    fired = _family_specs()["transfers"][2]
    reports = []
    for run in range(runs):
        r = _run_transfers(S.generate_transfers(seed))
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(fired(r),
                     f"transfers: a transfer family never fired ({r})")
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1,
                 f"transfers: non-reproducible: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_transfers(
                    S.falsification_transfer_plan(seed, broken=True))
            except InvariantViolation as e:
                caught = "TRANSFER-AVAILABILITY" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the BROKEN transfer kernel "
                         "was NOT caught by TransferAvailability")
    try:
        r = _run_transfers(
            S.falsification_transfer_plan(seed, broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the CORRECT "
                           f"transfer kernel tripped the invariant: "
                           f"{e}")
    else:
        ok &= _check(r["transfers_completed"] >= 1,
                     "falsification control: the directed transfer "
                     "never completed")
        print(json.dumps(
            {"falsification_control": "passed",
             "max_transfer_stall": r["max_transfer_stall"]}))

    if with_procs:
        from raftsql_tpu.chaos.proc import ProcTransferChaosRunner
        plan = S.generate_procs(seed, ticks=60)
        preports = []
        for run in range(runs):
            with tempfile.TemporaryDirectory(
                    prefix="raftsql-transfer-procs-") as d:
                r = ProcTransferChaosRunner(plan, d).run()
            r["run"] = run
            preports.append(r)
            print(json.dumps(r, sort_keys=True))
        for r in preports:
            ok &= _check(r["transfers_requested"] > 0
                         and r["transfers_completed"] > 0,
                         f"transfer-procs: no transfer completed over "
                         f"the public surface ({r})")
            ok &= _check(r["unexpected_exits"] == 0,
                         f"transfer-procs: unscripted server death "
                         f"({r})")
        pdig = {(r["schedule_digest"], r["result_digest"])
                for r in preports}
        ok &= _check(len(pdig) == 1,
                     f"transfer-procs: non-reproducible verdicts: "
                     f"{pdig}")
    if ok:
        print(f"chaos transfers ok: seed={seed} "
              f"schedule={reports[0]['schedule_digest']} "
              f"result={reports[0]['result_digest']} "
              f"falsification=caught")
    return 0 if ok else 1


def _run_reshard(plan) -> dict:
    from raftsql_tpu.chaos.scenarios import ReshardChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return ReshardChaosRunner(plan, d).run()


def run_reshard(seed: int, runs: int = 2) -> int:
    """`make chaos-reshard`: the elastic-keyspace gauntlet.

    1. The reshard nemesis (schedule.py generate_reshard), run twice —
       seeded split/merge/migrate schedules race partitions, drops,
       whole-cluster crash+restart, coordinator SIGKILL mid-verb and a
       disk fault on the migrate snapshot ship, under live acked-PUT
       load; schedule + result digests must reproduce and the
       NoAckedWriteLost / NoAvailabilityLoss invariants (plus the
       standing election-safety / durability / linearizability suite)
       must hold.  The schedule is REQUIRED to exercise every verb,
       at least one coordinator kill+recovery, and the fork-fault
       abort path.
    2. The FALSIFICATION pair (schedule.py falsification_reshard_plan):
       a coordinator variant that flips the router BEFORE the
       destination group durably applied the copied rows MUST be
       caught by NoAckedWriteLost on a directed schedule (the copy
       path starved by a partition anchored on the destination's
       leader), and the SAME schedule with the correct coordinator
       must complete the split cleanly — proving the harness detects
       exactly the premature flip, not chaos in general.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    reports = []
    for run in range(runs):
        r = _run_reshard(S.generate_reshard(seed))
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(r["reshard_splits"] >= 1
                     and r["reshard_merges"] >= 1
                     and r["reshard_migrations"] >= 1,
                     f"reshard: a verb family never completed ({r})")
        ok &= _check(r["coordinator_kills"] >= 1
                     and r["reshard_resumed"] >= 1,
                     f"reshard: no SIGKILL+journal-recovery cycle ({r})")
        ok &= _check(r["fork_faults"] >= 1
                     and r["reshard_aborted"] >= 1,
                     f"reshard: the disk-fault abort path never fired "
                     f"({r})")
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1,
                 f"reshard: non-reproducible: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_reshard(
                    S.falsification_reshard_plan(seed, broken=True))
            except InvariantViolation as e:
                caught = "NO-ACKED-WRITE-LOST" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the BROKEN premature router "
                         "flip was NOT caught by NoAckedWriteLost")
    try:
        r = _run_reshard(S.falsification_reshard_plan(seed,
                                                      broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the CORRECT "
                           f"coordinator tripped the invariant: {e}")
    else:
        ok &= _check(r["reshard_splits"] >= 1,
                     "falsification control: the directed split never "
                     "completed")
        print(json.dumps(
            {"falsification_control": "passed",
             "moved_checks": r["moved_checks"]}))
    if ok:
        print(f"chaos reshard ok: seed={seed} "
              f"schedule={reports[0]['schedule_digest']} "
              f"result={reports[0]['result_digest']} "
              f"falsification=caught")
    return 0 if ok else 1


def run_quorum(seed: int, runs: int = 2) -> int:
    """`make chaos-quorum`: the quorum-geometry gauntlet.

    1. The witness-cluster nemesis (schedule.py generate_quorum): two
       full voters + one witness, W = E = 2 explicit, under
       leader-targeted partitions, an asymmetric cut, clock skew and
       whole-cluster crash+restart with acked PUTs and lease/ReadIndex
       reads.  Run `runs` times — schedule + result digests must
       reproduce, the witness must replicate (witness_appends > 0)
       without ever publishing (witness_publishes == 0), and the
       report must show exactly one apply/shard stream fewer than WAL
       streams (the fsync economy the witness buys).
    2. FALSIFICATION arm A — non-intersecting quorums.  First the
       config gate: W=1/E=2 on 3 peers must be REFUSED without
       unsafe_quorum_geometry.  Then the directed plan
       (falsification_quorum_plan) with the gate bypassed: a
       partitioned pinned leader solo-commits acked writes the
       majority side then rewrites — the split MUST be caught
       (cross-peer changed-content / log matching / commit
       monotonicity / election safety).  The SAME schedule at W=2
       must pass.
    3. FALSIFICATION arm B — witness counted toward the lease quorum
       (falsification_witness_plan): unsafe_witness_lease lets the
       witness grant a prevote inside the deposed leader's live
       lease; the new leader's committed write then makes the old
       leader's lease read STALE, and the register invariant MUST
       fire.  The SAME schedule with the honest witness must pass.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation
    from raftsql_tpu.config import RaftConfig

    ok = True
    fired = _family_specs()["quorum"][2]
    reports = []
    for run in range(runs):
        r = _run_quorum(S.generate_quorum(seed))
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(fired(r),
                     f"quorum: a geometry signature never fired ({r})")
    digests = {(r["plan_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1,
                 f"quorum: non-reproducible: {digests}")

    # Arm A, config gate: the geometry the broken plan runs is refused
    # at construction unless explicitly bypassed.
    try:
        RaftConfig(num_groups=1, num_peers=3,
                   write_quorum=1, election_quorum=2)
    except ValueError as e:
        refused = "intersect" in str(e)
        print(json.dumps({"geometry_guard": "refused",
                          "error": str(e)}))
    else:
        refused = False
    ok &= _check(refused, "quorum: W=1/E=2 on 3 peers was NOT refused "
                          "at config time")

    # Falsification sensitivity proofs.  Violations are EXPECTED —
    # route their flight bundles to a temp dir instead of cwd.
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    caught_split = caught_stale = False
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_quorum(S.falsification_quorum_plan(seed,
                                                        broken=True))
            except InvariantViolation as e:
                caught_split = any(
                    m in str(e) for m in ("changed content",
                                          "diverge", "regressed",
                                          "two leaders"))
                print(json.dumps({"falsification": "caught",
                                  "arm": "non-intersecting",
                                  "violation": str(e)}))
            try:
                _run_quorum(S.falsification_witness_plan(seed,
                                                         broken=True))
            except InvariantViolation as e:
                caught_stale = "STALE" in str(e) or "stale" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "arm": "witness-lease",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught_split,
                 "falsification: the NON-INTERSECTING W=1/E=2 "
                 "geometry was NOT caught by any invariant")
    ok &= _check(caught_stale,
                 "falsification: the witness-in-lease-quorum bug was "
                 "NOT caught as a stale lease read")
    try:
        r = _run_quorum(S.falsification_quorum_plan(seed,
                                                    broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the CORRECT W=2 "
                           f"geometry tripped the invariant: {e}")
    else:
        ok &= _check(r["committed_entries"] > 0,
                     "falsification control: nothing committed under "
                     "the correct geometry")
        print(json.dumps({"falsification_control": "passed",
                          "arm": "non-intersecting",
                          "committed": r["committed_entries"]}))
    try:
        r = _run_quorum(S.falsification_witness_plan(seed,
                                                     broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the HONEST "
                           f"witness tripped the invariant: {e}")
    else:
        ok &= _check(r["lease_reads"] > 0,
                     "falsification control: no lease reads granted "
                     "under the honest witness")
        print(json.dumps({"falsification_control": "passed",
                          "arm": "witness-lease",
                          "lease_reads": r["lease_reads"]}))
    if ok:
        print(f"chaos quorum ok: seed={seed} "
              f"plan={reports[0]['plan_digest']} "
              f"result={reports[0]['result_digest']} "
              f"witness_appends={reports[0]['witness_appends']} "
              f"apply_streams={reports[0]['apply_streams']}/"
              f"{reports[0]['wal_streams']} falsification=caught(x2)")
    return 0 if ok else 1


def _run_overload(plan) -> dict:
    from raftsql_tpu.chaos.scenarios import OverloadChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-chaos-") as d:
        return OverloadChaosRunner(plan, d).run()


def run_overload(seed: int, runs: int = 2) -> int:
    """`make chaos-overload`: the overload-control gauntlet.

    1. The overload nemesis (schedule.py generate_overload): an
       open-loop producer offers ~2x the engine's drain rate — with
       burst windows, hot-group skew, device-step deadlines on a
       fraction of writes, slow-fsync stalls and a mid-overload
       crash+restart — against the bounded admission controller
       attached exactly as the server attaches it.  Run `runs` times:
       plan + result digests must reproduce, the propose backlog must
       never exceed the hard cap (OVERLOAD-MEMORY, measured against
       the engine's actual queues every tick), every acked write must
       survive the restart replay (the standing durability ledger),
       refusals and deadline stage-sheds must actually fire, goodput
       must clear the plan's floor despite the 2x offered load, and
       no group may be starved below the per-group floor.
    2. The FALSIFICATION pair (schedule.py
       falsification_overload_plan): the identical sustained-2x
       schedule with NO admission controller attached MUST be caught
       by OVERLOAD-MEMORY within the run, and the SAME schedule with
       the bounded controller must pass — proving the harness detects
       exactly the missing admission bound, not offered load in
       general.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    plan = S.generate_overload(seed)
    reports = []
    for run in range(runs):
        r = _run_overload(plan)
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(r["overload_rejected"] > 0
                     and r["overload_shed_stage"] > 0
                     and r["fsync_stalls"] > 0 and r["crashes"] >= 1,
                     f"overload: a pressure family never fired ({r})")
        ok &= _check(r["overload_depth_peak"] <= plan.total_cap,
                     f"overload: backlog peak "
                     f"{r['overload_depth_peak']} exceeded the cap "
                     f"{plan.total_cap} without tripping the "
                     f"invariant ({r})")
        ok &= _check(
            r["committed_entries"] >= plan.goodput_floor * plan.ticks,
            f"overload: goodput floor missed — "
            f"{r['committed_entries']} committed < "
            f"{plan.goodput_floor * plan.ticks} ({r})")
        ok &= _check(
            min(r["group_commits"]) >= plan.starvation_floor,
            f"overload: a group starved — per-group commits "
            f"{r['group_commits']} < floor {plan.starvation_floor} "
            f"({r})")
    digests = {(r["plan_digest"], r["result_digest"]) for r in reports}
    ok &= _check(len(digests) == 1,
                 f"overload: non-reproducible: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_overload(
                    S.falsification_overload_plan(seed, broken=True))
            except InvariantViolation as e:
                caught = "OVERLOAD-MEMORY" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the UNBOUNDED propose queue "
                         "was NOT caught by OVERLOAD-MEMORY")
    try:
        r = _run_overload(
            S.falsification_overload_plan(seed, broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the BOUNDED "
                           f"admission control tripped the invariant: "
                           f"{e}")
    else:
        ok &= _check(r["committed_entries"] > 0
                     and r["overload_rejected"] > 0,
                     "falsification control: nothing committed (or "
                     "nothing refused) under bounded admission")
        print(json.dumps({"falsification_control": "passed",
                          "committed": r["committed_entries"],
                          "rejected": r["overload_rejected"]}))
    if ok:
        print(f"chaos overload ok: seed={seed} "
              f"plan={reports[0]['plan_digest']} "
              f"result={reports[0]['result_digest']} "
              f"rejected={reports[0]['overload_rejected']} "
              f"shed_stage={reports[0]['overload_shed_stage']} "
              f"depth_peak={reports[0]['overload_depth_peak']}"
              f"/{plan.total_cap} (x{runs} identical) "
              f"falsification=caught")
    return 0 if ok else 1


def _run_pod(plan) -> dict:
    from raftsql_tpu.chaos.pod import PodChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-pod-") as d:
        return PodChaosRunner(plan, d).run()


def run_pod(seed: int, runs: int = 2) -> int:
    """`make chaos-pod`: the multi-host pod gauntlet.

    1. The pod nemesis (schedule.py generate_pod): a 2-process pod
       (chaos/pod.py — real OS processes lockstepped by the
       TcpPodTransport collective, one group shard durable per host)
       runs three incarnations of an acked-write workload: a
       propose-plane cut, SIGKILL of the non-coordinator host, SIGKILL
       of the coordinator, then a fault-free audit incarnation.  Every
       acked write must survive into the merged cross-host replay
       (durability), apply exactly once post-dedup (the re-offer retry
       tokens), and every host must fold to the identical state
       (convergence).  The seed runs `runs` times; plan + verdict
       digests must match (committed history crosses N real kernels —
       the proc-plane determinism tier).
    2. The FALSIFICATION pair (schedule.py falsification_pod_plan):
       acks written at OFFER time (before the collective, before any
       fsync) plus a scripted pre-durability crash MUST be caught by
       the durability invariant as acked writes missing from the audit
       fold — and the SAME schedule with honest post-publish acks must
       pass, proving the harness detects exactly the premature ack,
       not pod restarts in general.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    plan = S.generate_pod(seed)
    reports = []
    for run in range(runs):
        r = _run_pod(plan)
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(r["noncoord_kills"] >= 1 and r["coord_kills"] >= 1
                     and r["pod_lost_exits"] >= 1
                     and r["cut_deferred"] > 0,
                     f"pod: a scripted fault family never fired ({r})")
        ok &= _check(r["unexpected_exits"] == 0,
                     f"pod: a child died of something unscripted ({r})")
        ok &= _check(r["acked"] > 0 and r["folded_keys"] > 0,
                     f"pod: the workload never acked anything ({r})")
    digests = {(r["plan_digest"], r["result_digest"]) for r in reports}
    ok &= _check(len(digests) == 1,
                 f"pod: non-reproducible verdicts: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_pod(S.falsification_pod_plan(seed, broken=True))
            except InvariantViolation as e:
                caught = "DURABILITY" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the PREMATURE pod ack was "
                         "NOT caught by the durability invariant")
    try:
        r = _run_pod(S.falsification_pod_plan(seed, broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: honest acks "
                           f"tripped the invariant: {e}")
    else:
        ok &= _check(r["crash_exits"] >= 1 and r["acked"] > 0,
                     "falsification control: the crash point never "
                     "fired (or nothing acked)")
        print(json.dumps({"falsification_control": "passed",
                          "acked": r["acked"],
                          "crash_exits": r["crash_exits"]}))
    if ok:
        print(f"chaos pod ok: seed={seed} "
              f"plan={reports[0]['plan_digest']} "
              f"verdict={reports[0]['result_digest']} (x{runs} "
              f"identical) falsification=caught")
    return 0 if ok else 1


def _run_replica(plan) -> dict:
    from raftsql_tpu.chaos.replica import ReplicaChaosRunner
    with tempfile.TemporaryDirectory(prefix="raftsql-replica-") as d:
        return ReplicaChaosRunner(plan, d).run()


def run_replica(seed: int, runs: int = 2) -> int:
    """`make chaos-replica`: the read-replica tier gauntlet.

    1. The replica nemesis (schedule.py generate_replica): a fused
       engine publishing the shm delta stream (`--replica-listen`),
       two real `python -m raftsql_tpu.replica` processes subscribed
       through nemesis-owned TCP proxies, and a seeded fault timeline
       — a subscription CUT + HEAL, a replica SIGKILL + respawn, and
       one flipped stream bit — under an acked-write workload probing
       session + linear reads at every replica.  StaleReadNever: a
       200 answer below the mode's bound (session watermark / rows
       acked before a linear probe began) is the violation; a 421
       refusal never is.  The audit requires every replica to
       converge to the exact final counts and the corruption to have
       surfaced as a CRC failure.  Runs `runs` times; plan + verdict
       digests must match (proc-plane determinism tier — the history
       crosses real kernels and is not bit-stable).
    2. The FALSIFICATION pair (schedule.py
       falsification_replica_plan): one replica booted with
       --unsafe-serve (every fail-closed gate skipped) under a
       never-healed cut MUST be caught serving below an acked
       watermark by StaleReadNever — and the SAME schedule with the
       gates on must pass by refusing, proving the harness detects
       exactly the missing gate, not partitions in general.
    """
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.invariants import InvariantViolation

    ok = True
    plan = S.generate_replica(seed)
    reports = []
    for run in range(runs):
        r = _run_replica(plan)
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
        ok &= _check(r["cuts"] >= 1 and r["heals"] >= 1
                     and r["kills"] >= 1 and r["restarts"] >= 1
                     and r["corrupts"] >= 1,
                     f"replica: a scripted fault family never fired ({r})")
        ok &= _check(r["acked"] > 0
                     and r["served_session"] > 0
                     and r["served_linear"] > 0,
                     f"replica: the workload never served a read ({r})")
        ok &= _check(r["refusals"] > 0,
                     f"replica: the cut never forced a refusal ({r})")
    digests = {(r["plan_digest"], r["result_digest"]) for r in reports}
    ok &= _check(len(digests) == 1,
                 f"replica: non-reproducible verdicts: {digests}")

    # Falsification sensitivity proof.  The violation is EXPECTED —
    # route its flight bundle to a temp dir instead of littering cwd.
    caught = False
    flight_prev = os.environ.get("RAFTSQL_FLIGHT_DIR")
    try:
        with tempfile.TemporaryDirectory(
                prefix="raftsql-falsification-") as fd:
            os.environ["RAFTSQL_FLIGHT_DIR"] = fd
            try:
                _run_replica(S.falsification_replica_plan(
                    seed, broken=True))
            except InvariantViolation as e:
                caught = "STALE" in str(e)
                print(json.dumps({"falsification": "caught",
                                  "violation": str(e)}))
    finally:
        if flight_prev is None:
            os.environ.pop("RAFTSQL_FLIGHT_DIR", None)
        else:
            os.environ["RAFTSQL_FLIGHT_DIR"] = flight_prev
    ok &= _check(caught, "falsification: the gate-less replica was "
                         "NOT caught by StaleReadNever")
    try:
        r = _run_replica(S.falsification_replica_plan(seed, broken=False))
    except InvariantViolation as e:
        ok = _check(False, f"falsification control: the fail-closed "
                           f"ladder tripped the invariant: {e}")
    else:
        ok &= _check(r["refusals"] > 0 and r["acked"] > 0,
                     "falsification control: the cut never forced a "
                     "refusal (or nothing acked)")
        print(json.dumps({"falsification_control": "passed",
                          "acked": r["acked"],
                          "refusals": r["refusals"]}))
    if ok:
        print(f"chaos replica ok: seed={seed} "
              f"plan={reports[0]['plan_digest']} "
              f"verdict={reports[0]['result_digest']} (x{runs} "
              f"identical) falsification=caught")
    return 0 if ok else 1


def run_matrix(seed: int, only=None) -> int:
    specs = _family_specs()
    ok = True
    for name, (run_fn, deterministic, fired) in specs.items():
        if only and name not in only:
            continue
        reports = [run_fn(seed)]
        if deterministic:
            reports.append(run_fn(seed))
            ok &= _check(
                _digests(reports[0]) == _digests(reports[1]),
                f"family {name}: non-deterministic "
                f"({_digests(reports[0])} != {_digests(reports[1])})")
        ok &= _check(fired(reports[0]),
                     f"family {name}: signature fault never fired "
                     f"({reports[0]})")
        out = {"family": name, "seed": seed,
               "deterministic": deterministic, **reports[0]}
        print(json.dumps(out, sort_keys=True))
    if ok:
        print(f"chaos matrix ok: seed={seed} families="
              f"{','.join(only or specs)}")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int,
                    default=int(os.environ.get("SEED", "0")))
    ap.add_argument("--ticks", type=int, default=240)
    ap.add_argument("--runs", type=int, default=2,
                    help="repeat the seed and require identical digests")
    ap.add_argument("--steps", type=int, default=1,
                    help="fused steps per dispatch (epoch-framed when >1)")
    ap.add_argument("--matrix", action="store_true",
                    help="sweep one seed through every scenario family")
    ap.add_argument("--family", action="append", default=None,
                    help="run only this family (repeatable; implies "
                         "--matrix)")
    ap.add_argument("--procs", action="store_true",
                    help="process-plane nemesis over real server "
                         "processes (make chaos-procs)")
    ap.add_argument("--reads", action="store_true",
                    help="read-plane nemesis (make chaos-reads): the "
                         "fused lease/ReadIndex/session/follower "
                         "nemesis run twice + the lease-falsification "
                         "sensitivity pair + the process-plane read "
                         "nemesis")
    ap.add_argument("--transfers", action="store_true",
                    help="transfer-plane nemesis (make chaos-transfer):"
                         " the fused transfer-under-nemesis family run "
                         "twice + the broken-kernel falsification pair "
                         "+ the process-plane POST /transfer nemesis")
    ap.add_argument("--reshard", action="store_true",
                    help="elastic-keyspace nemesis (make chaos-reshard)"
                         ": seeded split/merge/migrate schedules under "
                         "fire, run twice + the premature-router-flip "
                         "falsification pair")
    ap.add_argument("--quorum", action="store_true",
                    help="quorum-geometry nemesis (make chaos-quorum):"
                         " the witness-cluster family run twice + the "
                         "non-intersecting-geometry and "
                         "witness-lease falsification pairs")
    ap.add_argument("--overload", action="store_true",
                    help="overload-control nemesis (make "
                         "chaos-overload): open-loop 2x offered load "
                         "with bursts, hot-group skew, deadlines and "
                         "slow-fsync stalls against the bounded "
                         "admission controller, run twice + the "
                         "no-admission falsification pair")
    ap.add_argument("--pod", action="store_true",
                    help="multi-host pod nemesis (make chaos-pod): "
                         "host SIGKILLs (non-coordinator + "
                         "coordinator) and a propose-plane cut over a "
                         "real 2-process pod, run twice + the "
                         "premature-ack falsification pair")
    ap.add_argument("--replica", action="store_true",
                    help="read-replica tier nemesis (make "
                         "chaos-replica): subscription cut/heal, "
                         "replica SIGKILL/respawn and stream "
                         "corruption over real replica processes, "
                         "run twice + the unsafe-serve "
                         "falsification pair")
    ap.add_argument("--no-procs", action="store_true",
                    help="with --reads/--transfers: skip the "
                         "process-plane leg")
    ap.add_argument("--proc-ticks", type=int,
                    default=int(os.environ.get("PROC_TICKS", "80")),
                    help="host ticks for the --procs script phase")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.reads:
        return run_reads(args.seed, runs=args.runs,
                         with_procs=not args.no_procs)
    if args.transfers:
        return run_transfers(args.seed, runs=args.runs,
                             with_procs=not args.no_procs)
    if args.reshard:
        return run_reshard(args.seed, runs=args.runs)
    if args.quorum:
        return run_quorum(args.seed, runs=args.runs)
    if args.overload:
        return run_overload(args.seed, runs=args.runs)
    if args.pod:
        return run_pod(args.seed, runs=args.runs)
    if args.replica:
        return run_replica(args.seed, runs=args.runs)
    if args.procs:
        return run_procs(args.seed, args.proc_ticks, runs=args.runs)
    if args.matrix or args.family:
        return run_matrix(args.seed, only=args.family)

    from raftsql_tpu.analysis.tripwire import JitTripwire
    from raftsql_tpu.chaos.schedule import generate

    sched = generate(args.seed, ticks=args.ticks)
    # Armed before the first dispatch; the verdict prints OUTSIDE the
    # digested reports (compile counts are host-side facts, and the
    # result digests must stay comparable across tripwire changes).
    tripwire = JitTripwire()
    reports = []
    for run in range(args.runs):
        r = _run_fused(sched, steps=args.steps)
        r["run"] = run
        reports.append(r)
        print(json.dumps(r, sort_keys=True))
    ok = _check(all(r["fsync_faults"] >= 1 and r["torn_writes"] >= 1
                    for r in reports),
                "a scheduled storage fault never fired")
    digests = {(r["schedule_digest"], r["result_digest"])
               for r in reports}
    ok &= _check(len(digests) == 1, f"non-deterministic run: {digests}")
    compiles = {k: v for k, v in tripwire.compiles().items()
                if v is not None and v > 0}
    print(f"jit-tripwire: {json.dumps(compiles, sort_keys=True)}")
    ok &= _check(not tripwire.offenders(limit=1),
                 f"jit entry point recompiled mid-run: "
                 f"{tripwire.offenders(limit=1)}")
    if ok:
        print(f"chaos ok: seed={args.seed} ticks={args.ticks} "
              f"schedule={reports[0]['schedule_digest']} "
              f"result={reports[0]['result_digest']} "
              f"(x{args.runs} identical)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
