"""Pod-plane chaos: a seeded nemesis over a REAL multi-process pod.

The pod runtime (raftsql_tpu/pod/) breaks the single-controller
assumption: N host processes jointly own the cluster, lockstepped by a
per-tick collective, each durable for its own group shards.  Its
failure model is FAIL-STOP AND POD-WIDE — one host dying kills the
whole program — which moves the recovery burden to the restart path:
the respawned pod must rebuild the identical global state from the
MERGED cross-host replay exchange.  That is exactly what this nemesis
attacks.  It drives N `raftsql_tpu.chaos.pod --child` processes
(TcpPodTransport between them, sharded WAL dirs per host) through a
seeded `PodChaosPlan` of INCARNATIONS:

  * SIGKILL of a NON-coordinator host — the coordinator's collective
    recv breaks mid-tick; it must abort the pod (PodPeerLost fan-out)
    rather than hang, and the respawned pod must recover every acked
    write from the dead host's surviving WAL dirs;
  * SIGKILL of the COORDINATOR host — the members' sockets break; the
    fail-fast path without the abort broadcast;
  * a PROPOSE-PLANE cut — one origin's client offers cannot reach the
    collective for a window (deferred, counted): availability degrades
    at one host without breaking any promise.  A transport-level cut
    is not a separate event on purpose: the pod is fail-stop, so a
    severed collective socket IS the kill path, already exercised.

Workload: each origin offers unique keyed writes ("{pid} SET
w{origin}x{n} h{origin}i{inc}"), pid strided by origin exactly like
the pod's proposal seqs so the existing ack plane routes it home.  The
owner of a group acks a write's pid only after the commit appears in
its post-barrier publish stream (durable by the pod tick contract);
the origin appends honest acks to an append-only ledger the nemesis
audits.  On reboot a child RE-OFFERS (same pid — the retry token)
every offered-unacked write absent from the replayed fold; the fold
dedups by pid, so a write that committed but lost its ack applies
exactly once.

After the final fault-free incarnation every host dumps its fold and
the nemesis checks:

  D  durability    — every acked (key, value) is in the audit fold;
  X  exactly-once  — every key applied exactly once (post-dedup);
  C  convergence   — all hosts' folds + hard-state digests identical.

Determinism tier matches the proc plane (the weakest, README fault
matrix): plan digest + invariant-verdict digest must reproduce across
runs of one seed; the committed history crosses N real kernels and is
not bit-stable.  The falsification pair (schedule.py
falsification_pod_plan): acks written at OFFER time plus a hard
pre-durability crash MUST be caught by D; the same schedule with
honest acks must pass.
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Set, Tuple

from raftsql_tpu.chaos.invariants import InvariantViolation
from raftsql_tpu.chaos.schedule import PodChaosPlan, PodKill, PodLinkCut

# Child exit codes: PodPeerLost (a peer died; the pod-wide fail-stop
# exit) and the falsification plan's injected pre-durability crash.
EXIT_POD_LOST = 75
EXIT_POD_CRASH = 73


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _plan_from_doc(doc: dict) -> PodChaosPlan:
    return PodChaosPlan(
        seed=doc["seed"], ticks=doc["ticks"], procs=doc["procs"],
        peers=doc["peers"], groups=doc["groups"],
        group_shards=doc["group_shards"],
        settle_ticks=doc["settle_ticks"],
        kills=tuple(PodKill(**k) for k in doc["kills"]),
        cuts=tuple(PodLinkCut(**c) for c in doc["cuts"]),
        unsafe_ack=doc["unsafe_ack"], crash_at=doc["crash_at"])


def _fault_incarnations(plan: PodChaosPlan) -> int:
    """How many incarnations carry scripted faults; the audit
    incarnation (fault-free, runs to completion, dumps the fold) is
    the one after the last of these."""
    n = 0
    for k in plan.kills:
        n = max(n, k.incarnation + 1)
    if plan.crash_at >= 0:
        n = max(n, 1)
    return n


# ======================================================================
# Child: one pod process under the nemesis
# ======================================================================


class _PodChild:
    """One pod host process.  Lives in the same module as the nemesis
    (ProcCluster spawns server/main.py; the pod child has no server —
    its whole job is the workload + the audit fold)."""

    def __init__(self, plan: PodChaosPlan, proc_id: int, coord: str,
                 workdir: str, incarnation: int):
        self.plan = plan
        self.proc_id = proc_id
        self.coord = coord
        self.workdir = workdir
        self.inc = incarnation
        self.offers_path = os.path.join(workdir,
                                        f"offers-p{proc_id}.log")
        self.acks_path = os.path.join(workdir, f"acks-p{proc_id}.log")
        self.progress_path = os.path.join(
            workdir, f"progress-i{incarnation}-p{proc_id}.json")
        self.dump_path = os.path.join(workdir, f"dump-p{proc_id}.json")
        # pid -> (key, value, group) for every offer THIS origin ever
        # made (append-only ledger, replayed at boot for re-offers).
        self.offered: Dict[int, Tuple[str, str, int]] = {}
        self.acked: Set[int] = set()
        # The audit fold: key -> value, post-dedup, plus bookkeeping.
        self.fold: Dict[str, str] = {}
        self.applied_counts: Dict[str, int] = {}
        self.seen_pids: Set[int] = set()
        self.dups_folded = 0
        self.deferred = 0
        self.reoffered = 0

    # -- persistent ledgers --------------------------------------------

    def _load_ledgers(self) -> None:
        if os.path.exists(self.offers_path):
            with open(self.offers_path, encoding="utf-8") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 4:
                        self.offered[int(parts[0])] = (
                            parts[1], parts[2], int(parts[3]))
        if os.path.exists(self.acks_path):
            with open(self.acks_path, encoding="utf-8") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 3:
                        self.acked.add(int(parts[0]))

    def _log_offer(self, f, pid: int, key: str, value: str,
                   group: int) -> None:
        f.write(f"{pid} {key} {value} {group}\n")
        f.flush()
        self.offered[pid] = (key, value, group)

    def _log_ack(self, f, pid: int) -> None:
        if pid in self.acked or pid not in self.offered:
            return
        key, value, _g = self.offered[pid]
        f.write(f"{pid} {key} {value}\n")
        f.flush()
        self.acked.add(pid)

    def _progress(self, it: int) -> None:
        tmp = self.progress_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"iter": it, "deferred": self.deferred,
                       "reoffered": self.reoffered}, f)
        os.replace(tmp, self.progress_path)

    # -- the node ------------------------------------------------------

    def _build_node(self):
        from raftsql_tpu.config import RaftConfig
        from raftsql_tpu.pod.config import PodConfig
        from raftsql_tpu.pod.node import PodClusterNode
        from raftsql_tpu.runtime.mesh import MeshConfig
        plan = self.plan
        pod = PodConfig(procs=plan.procs, proc_id=self.proc_id,
                        coordinator=self.coord)
        cfg = RaftConfig(num_groups=plan.groups, num_peers=plan.peers,
                         log_window=32, max_entries_per_msg=4,
                         election_ticks=10, heartbeat_ticks=1,
                         tick_interval_s=0.0, seed=7)
        mesh = MeshConfig(peer_shards=1,
                          group_shards=plan.group_shards).build()
        return PodClusterNode(
            pod, cfg, os.path.join(self.workdir, f"h{self.proc_id}"),
            mesh, seed=3, connect_timeout_s=60.0, io_timeout_s=120.0)

    def _absorb(self, node, ack_f, honest_acks: bool) -> None:
        """Drain peer 0's publish stream into the fold (dedup by pid)
        and run both sides of the ack plane: owner-side acks for
        commits in OWNED groups, origin-side ledger appends for acks
        the collective carried home."""
        import queue

        from raftsql_tpu.runtime.db import _expand_commit_item
        q = node.commit_q(0)
        ack_pids: List[int] = []
        while True:
            try:
                item = q.get_nowait()
            except queue.Empty:
                break
            if item is None or not isinstance(item, tuple):
                continue
            for (g, _i, data) in _expand_commit_item(item):
                text = data.decode("utf-8", "replace") \
                    if isinstance(data, (bytes, bytearray)) else str(data)
                parts = text.split()
                if len(parts) != 4 or parts[1] != "SET":
                    continue
                pid, key, value = int(parts[0]), parts[2], parts[3]
                if pid in self.seen_pids:
                    self.dups_folded += 1
                else:
                    self.seen_pids.add(pid)
                    self.fold[key] = value
                    self.applied_counts[key] = \
                        self.applied_counts.get(key, 0) + 1
                if honest_acks and node.owns_group(int(g)):
                    ack_pids.append(pid)
        if ack_pids:
            node.pod_send_ack(ack_pids)
        for pid in node.pod_take_acked():
            self._log_ack(ack_f, pid)

    # -- main ----------------------------------------------------------

    def run(self) -> int:
        from raftsql_tpu.pod.transport import PodPeerLost
        plan = self.plan
        self._load_ledgers()
        honest = not plan.unsafe_ack
        # In an incarnation with a scheduled kill the child never
        # finishes on its own: it paces (so the parent's progress poll
        # can land the SIGKILL at the scripted iteration) and loops
        # until killed — a kill that silently misses would turn the
        # fired-families verdict into a coin flip.
        has_kill = any(k.incarnation == self.inc for k in plan.kills)
        iter_s = 0.05 if has_kill else 0.0
        cuts = [c for c in plan.cuts if c.incarnation == self.inc
                and c.origin == self.proc_id]
        crash_here = plan.crash_at >= 0 and self.inc == 0

        try:
            node = self._build_node()
        except PodPeerLost:
            return EXIT_POD_LOST
        ack_f = open(self.acks_path, "a", encoding="utf-8")
        offer_f = open(self.offers_path, "a", encoding="utf-8")
        try:
            # Settle: elections + the replayed prefix's re-publish all
            # land before the workload starts (fixed tick count — every
            # host must run the same collective sequence).
            for _ in range(plan.settle_ticks):
                node.tick()
                self._absorb(node, ack_f, honest)
            # Re-offer pending writes the replay did not recover: same
            # pid (the retry token — the fold dedups a write that
            # committed but lost its ack).
            pending = [pid for pid in sorted(self.offered)
                       if pid not in self.acked
                       and pid not in self.seen_pids]
            n = len(self.offered)
            it = 0
            while True:
                self._progress(it)
                if any(c.start <= it < c.end for c in cuts):
                    self.deferred += 1        # propose plane severed
                else:
                    if pending:
                        pid = pending.pop(0)
                        key, value, group = self.offered[pid]
                        self.reoffered += 1
                    else:
                        pid = self.proc_id + n * plan.procs
                        key = f"w{self.proc_id}x{n}"
                        value = f"h{self.proc_id}i{self.inc}"
                        group = pid % plan.groups
                        n += 1
                        self._log_offer(offer_f, pid, key, value, group)
                    if plan.unsafe_ack:
                        self._log_ack(ack_f, pid)   # BROKEN: pre-durable
                    if crash_here and it == plan.crash_at:
                        # The falsification crash point: a hard exit
                        # AFTER the offer (and, under unsafe_ack, its
                        # premature ack) but BEFORE the collective ever
                        # carries it — the acked write cannot possibly
                        # be durable anywhere, so the durability
                        # invariant must catch it in the audit fold.
                        ack_f.flush()
                        offer_f.flush()
                        os._exit(EXIT_POD_CRASH)
                    node.pod_propose(
                        group, [f"{pid} SET {key} {value}".encode()])
                node.tick()
                self._absorb(node, ack_f, honest)
                it += 1
                if it >= plan.ticks and not has_kill:
                    break
                if iter_s:
                    time.sleep(iter_s)
            # Trailing settle: let in-flight commits land and the last
            # acks ride home, then dump the audit fold.
            for _ in range(plan.settle_ticks):
                node.tick()
                self._absorb(node, ack_f, honest)
            self._progress(it)
            self._dump(node)
            node.stop()
            return 0
        except PodPeerLost:
            try:
                node.stop()
            except Exception:
                pass
            return EXIT_POD_LOST
        finally:
            ack_f.close()
            offer_f.close()

    def _dump(self, node) -> None:
        import numpy as np
        hard = hashlib.sha256(
            np.ascontiguousarray(node._hard).tobytes()).hexdigest()[:16]
        doc = {"proc_id": self.proc_id, "incarnation": self.inc,
               "kv": self.fold, "applied_counts": self.applied_counts,
               "hard_digest": hard, "dups_folded": self.dups_folded,
               "deferred": self.deferred, "reoffered": self.reoffered,
               "pod": node.pod_doc()}
        tmp = self.dump_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, self.dump_path)


# ======================================================================
# Parent: the nemesis
# ======================================================================


class PodChaosRunner:
    """Drive a PodChaosPlan against a real N-process pod; module doc."""

    def __init__(self, plan: PodChaosPlan, workdir: str):
        self.plan = plan
        self.workdir = str(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        with open(os.path.join(self.workdir, "plan.json"), "w",
                  encoding="utf-8") as f:
            json.dump(plan.describe(), f, sort_keys=True)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        self.env_base = dict(
            os.environ, JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
            PYTHONPATH=repo_root + (
                os.pathsep + os.environ["PYTHONPATH"]
                if os.environ.get("PYTHONPATH") else ""))
        self.procs: List[Optional[subprocess.Popen]] = \
            [None] * plan.procs
        self.report = {
            "incarnations": 0, "kills": 0, "coord_kills": 0,
            "noncoord_kills": 0, "pod_lost_exits": 0, "crash_exits": 0,
            "unexpected_exits": 0, "acked": 0, "cut_deferred": 0,
            "reoffered": 0, "folded_keys": 0, "dups_folded": 0,
        }
        self.verdicts: Dict[str, str] = {}

    # -- child control -------------------------------------------------

    def _spawn_all(self, inc: int, coord: str) -> None:
        for i in range(self.plan.procs):
            logf = open(os.path.join(self.workdir,
                                     f"pod{i}.log"), "ab")
            self.procs[i] = subprocess.Popen(
                [sys.executable, "-m", "raftsql_tpu.chaos.pod",
                 "--child", "--proc-id", str(i), "--coord", coord,
                 "--workdir", self.workdir,
                 "--incarnation", str(inc)],
                cwd=self.workdir, env=self.env_base,
                stdout=logf, stderr=logf)
            logf.close()

    def _progress_iter(self, inc: int, proc: int) -> int:
        path = os.path.join(self.workdir,
                            f"progress-i{inc}-p{proc}.json")
        try:
            with open(path, encoding="utf-8") as f:
                return int(json.load(f)["iter"])
        except (OSError, ValueError, KeyError):
            return -1

    def _wait_all(self, deadline_s: float) -> List[Optional[int]]:
        deadline = time.monotonic() + deadline_s
        codes: List[Optional[int]] = [None] * self.plan.procs
        while time.monotonic() < deadline:
            for i, p in enumerate(self.procs):
                codes[i] = None if p is None else p.poll()
            if all(c is not None for c in codes):
                return codes
            time.sleep(0.05)
        for i, p in enumerate(self.procs):      # fail-safe teardown
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=15)
            codes[i] = None if p is None else p.poll()
        raise InvariantViolation(
            f"pod children failed to exit before the deadline "
            f"(codes so far: {codes})")

    def _score_exit(self, code: int, killed: bool) -> None:
        if killed:
            return                               # the scripted SIGKILL
        if code == EXIT_POD_LOST:
            self.report["pod_lost_exits"] += 1
        elif code == EXIT_POD_CRASH:
            self.report["crash_exits"] += 1
        elif code != 0:
            self.report["unexpected_exits"] += 1

    # -- incarnations --------------------------------------------------

    def _run_incarnation(self, inc: int) -> None:
        plan = self.plan
        coord = f"127.0.0.1:{_free_port()}"
        kills = [k for k in plan.kills if k.incarnation == inc]
        self._spawn_all(inc, coord)
        self.report["incarnations"] += 1
        killed: Set[int] = set()
        try:
            # Land every scripted SIGKILL once its target's progress
            # file shows it past the scripted iteration (children in a
            # kill incarnation loop until killed — the kill cannot be
            # missed, only late).
            deadline = time.monotonic() + 240.0
            for k in sorted(kills, key=lambda k: k.at_iter):
                while True:
                    if time.monotonic() > deadline:
                        raise InvariantViolation(
                            f"pod kill at iter {k.at_iter} of proc "
                            f"{k.proc} never became due "
                            f"(progress="
                            f"{self._progress_iter(inc, k.proc)})")
                    p = self.procs[k.proc]
                    if p is None or p.poll() is not None:
                        raise InvariantViolation(
                            f"pod proc {k.proc} died before its "
                            f"scripted kill (exit {p.poll()})")
                    if self._progress_iter(inc, k.proc) >= k.at_iter:
                        p.send_signal(signal.SIGKILL)
                        p.wait(timeout=15)
                        killed.add(k.proc)
                        self.report["kills"] += 1
                        if k.proc == 0:
                            self.report["coord_kills"] += 1
                        else:
                            self.report["noncoord_kills"] += 1
                        break
                    time.sleep(0.02)
            codes = self._wait_all(300.0)
        finally:
            for p in self.procs:
                if p is not None and p.poll() is None:
                    p.kill()
                    p.wait(timeout=15)
        expect_crash = plan.crash_at >= 0 and inc == 0
        for i, code in enumerate(codes):
            self._score_exit(code, killed=i in killed)
            if not kills and not expect_crash and code != 0:
                raise InvariantViolation(
                    f"pod proc {i} exited {code} in the fault-free "
                    f"incarnation {inc}: {self._log_tail(i)}")

    # -- the audit -----------------------------------------------------

    def _read_acked(self) -> Dict[int, Tuple[str, str]]:
        acked: Dict[int, Tuple[str, str]] = {}
        for i in range(self.plan.procs):
            path = os.path.join(self.workdir, f"acks-p{i}.log")
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) == 3:
                        acked[int(parts[0])] = (parts[1], parts[2])
        return acked

    def _audit(self) -> None:
        dumps = []
        for i in range(self.plan.procs):
            path = os.path.join(self.workdir, f"dump-p{i}.json")
            try:
                with open(path, encoding="utf-8") as f:
                    dumps.append(json.load(f))
            except OSError as e:
                raise InvariantViolation(
                    f"pod proc {i} produced no audit dump: {e}")
        acked = self._read_acked()
        self.report["acked"] = len(acked)
        fold = dumps[0]["kv"]
        self.report["folded_keys"] = len(fold)
        self.report["dups_folded"] = sum(d["dups_folded"]
                                         for d in dumps)
        self.report["cut_deferred"] = self._sum_progress("deferred")
        self.report["reoffered"] = self._sum_progress("reoffered")
        # C: every host folded the identical committed state.
        for d in dumps[1:]:
            if d["kv"] != fold or d["hard_digest"] != \
                    dumps[0]["hard_digest"] or \
                    d["applied_counts"] != dumps[0]["applied_counts"]:
                raise InvariantViolation(
                    f"pod hosts DIVERGED after the audit incarnation: "
                    f"proc {d['proc_id']} folded {len(d['kv'])} keys / "
                    f"hard {d['hard_digest']}, proc 0 folded "
                    f"{len(fold)} keys / hard "
                    f"{dumps[0]['hard_digest']}")
        self.verdicts["convergence"] = "pass"
        # D: every acked (key, value) survived into the fold.
        missing = {pid: (k, v) for pid, (k, v) in acked.items()
                   if fold.get(k) != v}
        if missing:
            sample = sorted(missing.items())[:5]
            raise InvariantViolation(
                f"pod DURABILITY violated: {len(missing)} acked "
                f"writes missing from the audit fold, e.g. {sample}")
        self.verdicts["durability"] = "pass"
        # X: every key applied exactly once post-dedup (a re-offer
        # that forgot its retry token would double-apply).
        multi = {k: c for k, c in dumps[0]["applied_counts"].items()
                 if c != 1}
        if multi:
            raise InvariantViolation(
                f"pod EXACTLY-ONCE violated: keys applied more than "
                f"once post-dedup: {sorted(multi.items())[:5]}")
        self.verdicts["exactly_once"] = "pass"

    def _sum_progress(self, field: str) -> int:
        total = 0
        for name in os.listdir(self.workdir):
            if name.startswith("progress-") and name.endswith(".json"):
                try:
                    with open(os.path.join(self.workdir, name),
                              encoding="utf-8") as f:
                        total += int(json.load(f).get(field, 0))
                except (OSError, ValueError):
                    pass
        return total

    def _log_tail(self, i: int, nbytes: int = 4096) -> str:
        path = os.path.join(self.workdir, f"pod{i}.log")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - nbytes))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    # -- flight + digest -----------------------------------------------

    def _flight_dump(self, err: BaseException) -> None:
        from raftsql_tpu.obs.flight import FlightRecorder
        bundle: dict = {"plan": self.plan.describe(),
                        "plan_digest": self.plan.digest(),
                        "report": dict(self.report), "logs": {},
                        "wal_dirs": {}}
        for i in range(self.plan.procs):
            bundle["logs"][i] = self._log_tail(i)
            d = os.path.join(self.workdir, f"h{i}")
            try:
                bundle["wal_dirs"][i] = sorted(
                    os.path.join(dp.replace(self.workdir, ""), f)
                    for dp, _dn, fs in os.walk(d) for f in fs)
            except OSError:
                bundle["wal_dirs"][i] = []
        FlightRecorder().dump(
            f"pod-seed{self.plan.seed}", repr(err), meta=bundle)

    def _verdict_digest(self) -> str:
        """What must reproduce across runs of one seed: the plan, the
        invariant verdicts, and which fault families fired (booleans —
        iteration counts are wall-clock-scheduled)."""
        r = self.report
        plan = self.plan
        doc = {
            "plan": plan.digest(),
            "invariants": dict(self.verdicts),
            "fired": {
                "noncoord_kill": r["noncoord_kills"] >= sum(
                    1 for k in plan.kills if k.proc != 0),
                "coord_kill": r["coord_kills"] >= sum(
                    1 for k in plan.kills if k.proc == 0),
                "cut_deferred": (r["cut_deferred"] > 0)
                == bool(plan.cuts),
                "pod_lost": (r["pod_lost_exits"] > 0)
                == bool(plan.kills),
                "crash_point": (r["crash_exits"] > 0)
                == (plan.crash_at >= 0),
                "unexpected_exits": r["unexpected_exits"] == 0,
            },
        }
        blob = json.dumps(doc, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def run(self) -> dict:
        try:
            n_fault = _fault_incarnations(self.plan)
            for inc in range(n_fault + 1):
                self._run_incarnation(inc)
            self._audit()
        except BaseException as e:
            self._flight_dump(e)
            raise
        return {"plan_digest": self.plan.digest(),
                "result_digest": self._verdict_digest(),
                "seed": self.plan.seed, **self.report}


# ======================================================================
# Child entry
# ======================================================================


def _child_main(argv) -> int:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--proc-id", type=int, required=True)
    ap.add_argument("--coord", required=True)
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--incarnation", type=int, required=True)
    args = ap.parse_args(argv)
    with open(os.path.join(args.workdir, "plan.json"),
              encoding="utf-8") as f:
        plan = _plan_from_doc(json.load(f))
    child = _PodChild(plan, args.proc_id, args.coord, args.workdir,
                      args.incarnation)
    return child.run()


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1:]))
