"""Elastic keyspace: split/merge/migrate over the fixed device plane.

The compiled [P, G] device kernels keep a boot-time G; elasticity lives
one layer up.  Keys hash onto a fixed ring of slots and a versioned
`KeyMap` (slot -> group, epoch-stamped) decides which raft group owns
each slot.  The reshard coordinator moves slots between groups with
three multi-step verbs — SPLIT, MERGE, MIGRATE — journaled through the
raft logs themselves, so a coordinator killed at any step resumes (or
aborts cleanly) from the journal fold, never half-applies.
"""
from .keymap import KeyMap, slot_of
from .journal import (JournalRecord, decode_record, encode_record,
                      fold_records)
from .coordinator import ReshardCoordinator, ReshardRefused
from .fork import fork_by_slots
from .plane import FrozenSlot, ReshardPlane, WrongEpoch

__all__ = [
    "KeyMap", "slot_of",
    "JournalRecord", "encode_record", "decode_record", "fold_records",
    "ReshardCoordinator", "ReshardRefused",
    "fork_by_slots",
    "ReshardPlane", "WrongEpoch", "FrozenSlot",
]
