"""Serving-plane reshard: the coordinator wired to a live RaftDB.

`ReshardPlane` adapts the chaos-proven `ReshardCoordinator` step
machine onto the real serving stack: journal records are replicated as
rows of the `_reshard_journal` table THROUGH the raft log of the source
group (exactly as durable and ordered as the data they govern, and
carried inside every snapshot/fork — META_TABLES in fork.py), copies
are replicated `INSERT OR REPLACE` statements into the destination
group's log, the router is the shared `KeyMap` the /kv surface and the
worker shm plane consult, and MIGRATE ships a real
`SQLiteStateMachine.serialize` image through the fault-injectable fsio
plane before cutting the leader over with the existing catch-up-gated
transfer kernel.

Intake model (vs the chaos plane's in-log fence): the /kv surface
routes by the keymap and REFUSES writes to frozen slots up front
(503, client retries after the verb), so the drain step only has to
wait out writes already in flight at freeze time — applied catching
the group's commit watermark with no pending acks left.  The chaos
harness proves the stronger in-log-fence variant; this plane trades it
for zero per-statement overhead on the hot path, which is sound
because frozen-slot intake is refused BEFORE propose.

Clients fail closed on the mapping epoch: every /kv response carries
`X-Raft-Keymap-Epoch`, a request pinned to a stale epoch is refused
with 409 + the current keymap document, and `api/client.py` refreshes
its cached mapping from /healthz instead of guessing.

Crash recovery: `recover_from_db()` folds every group's journal table
(rebuilt by WAL replay / snapshot install before RaftDB's constructor
returns) and resumes or aborts the active verb — the same
`fold_records` path the chaos nemesis SIGKILLs against.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from raftsql_tpu.storage import fsio

from .coordinator import ReshardCoordinator
from .journal import decode_record, encode_record
from .keymap import DEFAULT_NSLOTS, KeyMap, slot_of

log = logging.getLogger("raftsql.reshard")

JOURNAL_DDL = ("CREATE TABLE IF NOT EXISTS _reshard_journal "
               "(rec TEXT NOT NULL)")

# Proposal ack budget for plane-internal writes (journal records, row
# copies, range deletes).  Generous: these ride the same log as client
# traffic and starvation is retried by the coordinator anyway.
ACK_TIMEOUT_S = 5.0


def _sql_str(s: str) -> str:
    return "'" + str(s).replace("'", "''") + "'"


class WrongEpoch(Exception):
    """A /kv request pinned a stale (or future) keymap epoch — the
    caller must refresh its mapping and retry (fail closed, never serve
    a key the router may have moved)."""

    def __init__(self, have: int, want: int):
        super().__init__(f"keymap epoch mismatch: request pinned "
                         f"{want}, serving {have}")
        self.have = have
        self.want = want


class FrozenSlot(Exception):
    """The key's slot is mid-reshard; intake is refused (retryable)."""

    def __init__(self, key: str, slot: int):
        super().__init__(f"key {key!r} (slot {slot}) is resharding; "
                         f"retry after the verb resolves")
        self.key = key
        self.slot = slot


class ReshardPlane:
    """Reshard coordinator + router for one RaftDB node.

    Thread model: HTTP/ring/admin threads call `route_*`/`enqueue`/
    `doc`; one driver thread (started by `start`, or the owner calls
    `step` directly in tests) advances the coordinator.  The KeyMap is
    only mutated inside the coordinator (under its lock); readers
    snapshot `epoch` first and fail closed on mismatch at response
    time, so a torn read of slots mid-flip cannot serve the wrong
    group silently.
    """

    def __init__(self, db, nslots: int = DEFAULT_NSLOTS,
                 ship_dir: Optional[str] = None,
                 table: str = "kv", keycol: str = "k",
                 valcol: str = "v",
                 step_interval_s: float = 0.02):
        self.db = db
        self.table = table
        self.keycol = keycol
        self.valcol = valcol
        self.step_interval_s = step_interval_s
        self.ship_dir = ship_dir or os.path.join(
            getattr(db, "data_dir", "."), "reshard-ship")
        self.keymap = KeyMap.initial(db.num_groups, nslots)
        wit = getattr(getattr(db.pipe, "node", None), "cfg", None)
        self.coord = ReshardCoordinator(
            self, self.keymap, num_groups=db.num_groups,
            clock=time.monotonic,
            witness_peers=tuple(wit.witness_set) if wit is not None
            else ())
        self._ddl_done: set = set()      # groups with the journal table
        self._kv_ddl_done: set = set()   # groups with the kv table
        # Per-slot PUT counters feeding split-hottest's partition
        # choice (placement/controller.py).  Bare int increments from
        # serving threads: a lost update only skews an advisory load
        # estimate, never routing — not worth a hot-path lock.
        self.slot_hits = [0] * int(nslots)
        self._jwant: Dict[tuple, int] = {}
        self._cutover_at: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        db.reshard = self

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        self.recover_from_db()
        self._thread = threading.Thread(target=self._drive, daemon=True,
                                        name="reshard-coordinator")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _drive(self) -> None:
        while not self._stop.is_set():
            try:
                self.coord.step()
            except Exception:                           # noqa: BLE001
                log.exception("reshard step failed; verb keeps retrying")
            self._stop.wait(self.step_interval_s)

    def step(self) -> None:
        self.coord.step()

    def recover_from_db(self) -> None:
        """Fold every group's replicated journal table (rebuilt by WAL
        replay before RaftDB's constructor returned) and resume/abort
        the active verb — the restarted-coordinator path."""
        records: List[dict] = []
        for g in range(self.db.num_groups):
            for rec in self._journal_rows(g):
                records.append(rec)
            if records:
                self._ddl_done.add(g)
        if records:
            self.coord.recover(records)

    # -- routing (the /kv surface) -------------------------------------

    def kv_put(self, key: str, value: str,
               epoch: Optional[int] = None):
        """Route a keyed write: epoch fail-closed check, frozen-slot
        refusal, then (group, sql) for the caller to propose.  Ensures
        the kv table exists on the target group first (idempotent DDL
        through the same log)."""
        self.check_epoch(epoch)
        g = self.group_for_write(key)
        self._ensure_kv(g)
        sql = (f"INSERT OR REPLACE INTO {self.table} "
               f"({self.keycol}, {self.valcol}) VALUES "
               f"({_sql_str(key)}, {_sql_str(value)})")
        return g, sql

    def kv_get(self, key: str, epoch: Optional[int] = None):
        """Route a keyed read: (group, sql).  Reads on frozen slots
        still serve (the source keeps the rows until the flip; after
        the flip the new epoch routes here to the destination).  The
        value is selected hex-encoded so the query plane's pipe-
        delimited row rendering cannot tear a value containing '|' —
        kv_value() decodes the response."""
        self.check_epoch(epoch)
        g = self.group_for_read(key)
        sql = (f"SELECT hex({self.valcol}) FROM {self.table} "
               f"WHERE {self.keycol} = {_sql_str(key)}")
        return g, sql

    @staticmethod
    def kv_value(rendered: str) -> Optional[str]:
        """Decode a kv_get response row (`|<hex>|\\n`) back to the
        value; None when the key does not exist (no rows)."""
        line = rendered.strip()
        if not line:
            return None
        return bytes.fromhex(line.strip("|")).decode("utf-8")

    def _ensure_kv(self, group: int) -> None:
        if group in self._kv_ddl_done:
            return
        self._propose(group,
                      f"CREATE TABLE IF NOT EXISTS {self.table} "
                      f"({self.keycol} TEXT PRIMARY KEY, "
                      f"{self.valcol} TEXT)")
        self._kv_ddl_done.add(group)

    def check_epoch(self, epoch: Optional[int]) -> int:
        """Fail closed: a request pinned to any epoch but the current
        one is refused with the current mapping attached."""
        have = self.keymap.epoch
        if epoch is not None and int(epoch) != have:
            raise WrongEpoch(have, int(epoch))
        return have

    def group_for_write(self, key: str) -> int:
        s = self.keymap.slot_of(key)
        if s in self.keymap.frozen:
            raise FrozenSlot(key, s)
        self.slot_hits[s] += 1
        return self.keymap.slots[s]

    def group_for_read(self, key: str) -> int:
        return self.keymap.group_of(key)

    # -- admin ---------------------------------------------------------

    def enqueue(self, verb: str, src: int, dst: int,
                slots=None) -> dict:
        vid = self.coord.enqueue(verb, src, dst, slots)
        return {"id": vid, "verb": verb, "src": int(src),
                "dst": int(dst), "epoch": self.keymap.epoch}

    def doc(self) -> dict:
        d = self.coord.doc()
        d["table"] = self.table
        return d

    def metrics_doc(self) -> dict:
        return self.coord.metrics_doc()

    # -- coordinator backend -------------------------------------------
    # All plane-internal reads go through the local state machine (the
    # apply thread's view): "applied" for this node IS the coordinator's
    # durability fence, same as the chaos runner's peer-0 stream.

    def _rows(self, group: int, sql: str) -> List[tuple]:
        sm = self.db._sms[group]
        fn = getattr(sm, "rows", None)
        if fn is not None:
            return fn(sql)
        out = []
        for line in sm.query(sql).splitlines():
            if line.startswith("|") and line.endswith("|"):
                out.append(tuple(line[1:-1].split("|")))
        return out

    def _journal_rows(self, group: int) -> List[dict]:
        try:
            raw = self._rows(group,
                             "SELECT rec FROM _reshard_journal")
        except Exception:                               # noqa: BLE001
            return []            # table not created yet on this group
        out = []
        for (payload,) in raw:
            rec = decode_record(payload)
            if rec is not None:
                out.append(rec)
        return out

    def _propose(self, group: int, sql: str) -> None:
        """Fire a plane-internal statement into a group's log.  Waits
        briefly for the ack (starvation is fine — every caller in the
        coordinator re-proposes idempotently on its retry cadence)."""
        fut = self.db.propose(sql, group)
        try:
            err = fut.wait(ACK_TIMEOUT_S)
            if err is not None:
                log.warning("reshard proposal %r on group %d: %s",
                            sql[:64], group, err)
        except TimeoutError:
            self.db.abandon(sql, group, fut)

    def _ensure_ddl(self, group: int) -> None:
        if group in self._ddl_done:
            return
        self._propose(group, JOURNAL_DDL)
        self._ddl_done.add(group)

    def journal(self, group: int, rec: dict, want: bool = True) -> None:
        group = int(group)
        if want:
            self._jwant[(int(rec["id"]), rec["step"])] = group
        self._ensure_ddl(group)
        self._propose(group,
                      f"INSERT INTO _reshard_journal (rec) VALUES "
                      f"({_sql_str(encode_record(rec))})")

    def journal_applied(self, vid: int, step: str) -> bool:
        g = self._jwant.get((int(vid), step))
        if g is None:
            return False
        for rec in self._journal_rows(g):
            if int(rec.get("id", -1)) == int(vid) \
                    and rec.get("step") == step:
                return True
        return False

    def drained(self, group: int, slots) -> bool:
        """Every write in flight at freeze time has applied: the local
        apply reached the group's current commit watermark and no acks
        are pending for the group.  New intake for the moving slots is
        already refused at the router (FrozenSlot)."""
        group = int(group)
        if self.db.pending_for(group):
            return False
        wm_fn = getattr(self.db.pipe.node, "commit_watermark", None)
        if wm_fn is None:
            return True
        return self.db.watermark(group) >= int(wm_fn(group))

    def rows_of(self, group: int, slots) -> Dict[str, str]:
        ss = set(int(s) for s in slots)
        out = {}
        for k, v in self._rows(
                int(group),
                f"SELECT {self.keycol}, {self.valcol} "
                f"FROM {self.table}"):
            if slot_of(str(k), self.keymap.nslots) in ss:
                out[str(k)] = str(v)
        return out

    def copy(self, dst: int, rows: Dict[str, str]) -> None:
        if not rows:
            return
        values = ", ".join(
            f"({_sql_str(k)}, {_sql_str(v)})"
            for k, v in sorted(rows.items()))
        self._propose(
            int(dst),
            f"INSERT OR REPLACE INTO {self.table} "
            f"({self.keycol}, {self.valcol}) VALUES {values}")

    def copy_settled(self, dst: int, rows: Dict[str, str]) -> bool:
        if not rows:
            return True
        have = self.rows_of(dst, set(
            slot_of(k, self.keymap.nslots) for k in rows))
        return all(have.get(k) == v for k, v in rows.items())

    def rdel(self, group: int, slots, vid: int) -> None:
        keys = sorted(self.rows_of(group, slots))
        if not keys:
            return
        inlist = ", ".join(_sql_str(k) for k in keys)
        self._propose(int(group),
                      f"DELETE FROM {self.table} "
                      f"WHERE {self.keycol} IN ({inlist})")

    def rdel_settled(self, group: int, slots, vid: int) -> bool:
        return not self.rows_of(group, slots)

    def publish(self, keymap: KeyMap) -> None:
        """New routing epoch: mirror it into the shm snapshot plane so
        worker readers fail closed on the next refresh."""
        shm = getattr(self.db, "shm", None)
        if shm is not None:
            set_epoch = getattr(shm, "set_keymap_epoch", None)
            if set_epoch is not None:
                try:
                    set_epoch(keymap.epoch)
                except Exception:                       # noqa: BLE001
                    log.exception("shm keymap epoch publish failed")

    # -- migrate -------------------------------------------------------

    def ship(self, group: int, target: int) -> None:
        """Write the group's snapshot image into the ship directory
        through the fault-injectable fsio plane (a failed fsync aborts
        the verb — the target never saw a partial image it could
        mistake for a shard)."""
        sm = self.db._sms[int(group)]
        index, image = sm.serialize_with_index()
        os.makedirs(self.ship_dir, exist_ok=True)
        path = os.path.join(self.ship_dir,
                            f"g{int(group)}-p{int(target)}-"
                            f"i{index}.img")
        with open(path, "wb") as f:
            fsio.write(f, image)
            fsio.fsync_file(f)
        fsio.fsync_dir(self.ship_dir)

    def cutover(self, group: int, target: int,
                retry: bool = False) -> Optional[str]:
        node = self.db.pipe.node
        group, target = int(group), int(target)
        if node.leader_of(group) == target:
            self._cutover_at = None
            return "completed"
        if self._cutover_at is None or retry:
            try:
                self.db.transfer(group, target)
                self._cutover_at = time.monotonic()
            except Exception:                           # noqa: BLE001
                # Not leader here / transfer refused: the coordinator
                # retries on its starvation cadence.
                if self._cutover_at is None:
                    self._cutover_at = time.monotonic()
        if time.monotonic() - self._cutover_at > 30.0:
            self._cutover_at = None
            return "aborted"
        return None
