"""Versioned key->group mapping: the router state of the elastic keyspace.

Keys hash onto a fixed ring of NSLOTS slots (crc32, Redis-cluster
style); the KeyMap assigns each slot to a raft group and stamps every
change with a monotonically increasing epoch.  The map itself is
DERIVED state: it can always be rebuilt by folding the reshard journal
records out of the raft logs (journal.fold_records), which is what
makes the coordinator crash-recoverable — the router never holds truth
the logs don't.

Consumers fail closed on epoch mismatch: a client or shm reader that
pinned epoch E refuses to serve a key once the published epoch moved,
and refreshes from /healthz instead of guessing.
"""
from __future__ import annotations

import zlib
from typing import Dict, Iterable, List, Set

DEFAULT_NSLOTS = 64


def slot_of(key: str, nslots: int = DEFAULT_NSLOTS) -> int:
    """Stable hash slot for a key (crc32 mod nslots)."""
    return zlib.crc32(key.encode("utf-8")) % nslots


class KeyMap:
    """slot -> group assignment with an epoch that bumps on every change.

    Mutating verbs (`move`, `retire`) bump the epoch; `freeze` /
    `unfreeze` mark slots whose ownership is in flight (intake refused)
    without bumping it — freezing is coordinator-local hygiene, not a
    routing change.
    """

    def __init__(self, nslots: int, slots: List[int], epoch: int = 0,
                 retired: Iterable[int] = ()):
        if len(slots) != nslots:
            raise ValueError("slot table length != nslots")
        self.nslots = int(nslots)
        self.slots = list(int(g) for g in slots)
        self.epoch = int(epoch)
        self.retired: Set[int] = set(int(g) for g in retired)
        self.frozen: Set[int] = set()

    # -- construction ------------------------------------------------
    @classmethod
    def initial(cls, num_groups: int, nslots: int = DEFAULT_NSLOTS) -> "KeyMap":
        """Boot-time map: slot s -> group s mod G (uniform stripe)."""
        return cls(nslots, [s % num_groups for s in range(nslots)], epoch=0)

    def copy(self) -> "KeyMap":
        km = KeyMap(self.nslots, self.slots, self.epoch, self.retired)
        km.frozen = set(self.frozen)
        return km

    # -- routing -----------------------------------------------------
    def slot_of(self, key: str) -> int:
        return slot_of(key, self.nslots)

    def group_of(self, key: str) -> int:
        return self.slots[self.slot_of(key)]

    def slots_of(self, group: int) -> List[int]:
        return [s for s, g in enumerate(self.slots) if g == group]

    def is_frozen(self, key: str) -> bool:
        return self.slot_of(key) in self.frozen

    def live_groups(self) -> List[int]:
        return sorted(set(self.slots) - self.retired)

    # -- mutation (coordinator only) ---------------------------------
    def move(self, slots: Iterable[int], dst: int) -> int:
        """Reassign `slots` to group `dst`; returns the new epoch."""
        for s in slots:
            self.slots[int(s)] = int(dst)
        self.retired.discard(int(dst))
        self.epoch += 1
        return self.epoch

    def retire(self, group: int) -> int:
        """Mark a group as holding no slots (post-merge).  The device
        plane keeps ticking the group; the router just never sends it
        keys until a future split revives it."""
        if any(g == group for g in self.slots):
            raise ValueError("cannot retire a group that still owns slots")
        self.retired.add(int(group))
        self.epoch += 1
        return self.epoch

    def freeze(self, slots: Iterable[int]) -> None:
        self.frozen.update(int(s) for s in slots)

    def unfreeze(self, slots: Iterable[int]) -> None:
        self.frozen.difference_update(int(s) for s in slots)

    # -- wire form ---------------------------------------------------
    def to_doc(self) -> Dict:
        return {
            "epoch": self.epoch,
            "nslots": self.nslots,
            "slots": list(self.slots),
            "retired": sorted(self.retired),
            "frozen": sorted(self.frozen),
        }

    @classmethod
    def from_doc(cls, doc: Dict) -> "KeyMap":
        km = cls(int(doc["nslots"]), [int(g) for g in doc["slots"]],
                 epoch=int(doc["epoch"]), retired=doc.get("retired", ()))
        km.frozen = set(int(s) for s in doc.get("frozen", ()))
        return km
