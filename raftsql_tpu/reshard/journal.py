"""Reshard journal: verb steps as ordinary replicated log entries.

Every step of a reshard verb is journaled by proposing a marker entry
through the SOURCE group's raft log (the "split entry" of the paper
sketch): `RJ!{json}`.  The journal is therefore exactly as durable and
as ordered as the data it governs — there is no side file that can
disagree with the logs after a crash.  A restarted coordinator folds
the applied journal records back into (keymap, active-verb) and resumes
the verb from its last journaled step, or aborts it if the copy phase
never completed.

Record shape (all fields ints except strings noted):
  {"id": verb-id (monotone), "verb": "split"|"merge"|"migrate",
   "step": "begin"|"copied"|"shipped"|"flip"|"done"|"abort",
   "src": group, "dst": group-or-peer, "slots": [slot...],
   "nslots": ring size}

The companion `RD!{json}` record is a range-delete command: the group
applying it deletes every key whose slot is listed (cleanup on the
source after a flip, or undo of partial copies on the destination
after an abort).
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Tuple

from .keymap import KeyMap

JOURNAL_PREFIX = "RJ!"
RDEL_PREFIX = "RD!"

# Step vocabulary, in verb order.  "copied" is only journaled once the
# destination group has APPLIED every copied row — journaling it is the
# durability fence the router flip waits behind.
STEPS = ("begin", "copied", "shipped", "flip", "done", "abort")
TERMINAL = ("done", "abort")


class JournalRecord(dict):
    """A journal record is a plain dict; this subclass only exists to
    give isinstance checks a name."""


def encode_record(rec: Dict) -> str:
    return JOURNAL_PREFIX + json.dumps(rec, sort_keys=True,
                                       separators=(",", ":"))


def decode_record(payload) -> Optional[Dict]:
    """Parse an `RJ!` journal payload; None if it is not one."""
    if isinstance(payload, (bytes, bytearray)):
        try:
            payload = payload.decode("utf-8")
        except UnicodeDecodeError:
            return None
    if not isinstance(payload, str) or not payload.startswith(JOURNAL_PREFIX):
        return None
    try:
        rec = json.loads(payload[len(JOURNAL_PREFIX):])
    except ValueError:
        return None
    return rec if isinstance(rec, dict) and "step" in rec else None


def encode_rdel(slots: Iterable[int], nslots: int, verb_id: int) -> str:
    return RDEL_PREFIX + json.dumps(
        {"id": int(verb_id), "slots": sorted(int(s) for s in slots),
         "nslots": int(nslots)},
        sort_keys=True, separators=(",", ":"))


def decode_rdel(payload) -> Optional[Dict]:
    if isinstance(payload, (bytes, bytearray)):
        try:
            payload = payload.decode("utf-8")
        except UnicodeDecodeError:
            return None
    if not isinstance(payload, str) or not payload.startswith(RDEL_PREFIX):
        return None
    try:
        doc = json.loads(payload[len(RDEL_PREFIX):])
    except ValueError:
        return None
    return doc if isinstance(doc, dict) and "slots" in doc else None


def fold_records(records: Iterable[Dict], num_groups: int,
                 nslots: int) -> Tuple[KeyMap, Optional[Dict]]:
    """Fold applied journal records into (keymap, active_verb).

    `records` may arrive in any order and contain duplicates (a nervous
    coordinator re-proposes idempotently); the fold sorts by (id, step
    rank) and collapses repeats.  `active_verb` is the latest verb with
    no terminal record — the verb a restarted coordinator must resume
    or abort — as {"id", "verb", "src", "dst", "slots", "steps": set}.
    """
    by_id: Dict[int, Dict] = {}
    for rec in records:
        if rec is None or "id" not in rec:
            continue
        vid = int(rec["id"])
        slot = by_id.setdefault(vid, {"id": vid, "steps": set()})
        slot["steps"].add(rec["step"])
        for k in ("verb", "src", "dst", "slots"):
            if k in rec:
                slot.setdefault(k, rec[k])
    km = KeyMap.initial(num_groups, nslots)
    km.epoch = 0
    active: Optional[Dict] = None
    for vid in sorted(by_id):
        v = by_id[vid]
        steps = v["steps"]
        if "flip" in steps:
            km.move(v.get("slots", ()), int(v["dst"]))
            if v.get("verb") == "merge":
                try:
                    km.retire(int(v["src"]))
                except ValueError:
                    pass        # src re-acquired slots in a later verb
        if not steps & set(TERMINAL):
            active = v          # at most one in flight; latest wins
    if active is not None and "flip" not in active["steps"] \
            and active.get("verb") != "migrate":
        km.freeze(active.get("slots", ()))
    return km, active
