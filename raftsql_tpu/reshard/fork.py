"""Snapshot fork: split one SQLite image into two disjoint shards.

A SPLIT freezes intake on the source group, takes a consistent image
(`SQLiteStateMachine.serialize`, which already handles the py3.10
`VACUUM INTO` fallback), and forks it by hash slot: every row of the
keyed table whose key hashes into the moving slot set goes to the new
group's image, the rest stay.  The two outputs are real standalone
SQLite files whose keyed-row union is exactly the source — the
disjoint-union property tests/test_reshard.py pins.

The fork works purely through file-backed connections and an ATTACHed
source, so it runs identically on py3.10 (no Connection.serialize /
deserialize) and newer interpreters.
"""
from __future__ import annotations

import os
import shutil
import sqlite3
import tempfile
from typing import Iterable, Tuple

from .keymap import slot_of

# Tables that are replication plumbing, not user data: they are copied
# to BOTH forks verbatim (each side keeps its applied floor / journal).
META_TABLES = ("_raft_meta", "_reshard_journal")


def _copy_side(srcp: str, outp: str, table: str, keycol: str,
               slots: frozenset, nslots: int, keep_moving: bool) -> bytes:
    conn = sqlite3.connect(outp)
    try:
        conn.create_function(
            "raftslot", 1, lambda k: slot_of(str(k), nslots))
        conn.execute("ATTACH DATABASE ? AS src", (srcp,))
        rows = conn.execute(
            "SELECT name, sql FROM src.sqlite_master "
            "WHERE type='table' AND sql IS NOT NULL").fetchall()
        slotlist = ",".join(str(s) for s in sorted(slots)) or "-1"
        pred = "IN" if keep_moving else "NOT IN"
        for name, sql in rows:
            if name.startswith("sqlite_"):
                continue
            conn.execute(sql)
            if name == table:
                conn.execute(
                    f"INSERT INTO {name} SELECT * FROM src.{name} "
                    f"WHERE raftslot({keycol}) {pred} ({slotlist})")
            elif name in META_TABLES:
                conn.execute(
                    f"INSERT INTO {name} SELECT * FROM src.{name}")
            # other user tables are not slot-addressable; they stay with
            # the source shard only
            elif not keep_moving:
                conn.execute(
                    f"INSERT INTO {name} SELECT * FROM src.{name}")
        conn.commit()
        conn.execute("DETACH DATABASE src")
        conn.execute("VACUUM")
    finally:
        conn.close()
    with open(outp, "rb") as f:
        return f.read()


def fork_by_slots(image: bytes, slots: Iterable[int], nslots: int,
                  table: str = "kv",
                  keycol: str = "k") -> Tuple[bytes, bytes]:
    """Fork a serialized SQLite image by hash slot.

    Returns `(moving, staying)` images: `moving` holds exactly the
    keyed rows whose slot is in `slots`, `staying` holds the rest plus
    every non-keyed table.  Both carry the meta tables unchanged.
    """
    moving_set = frozenset(int(s) for s in slots)
    d = tempfile.mkdtemp(prefix="raftsql-fork-")
    try:
        srcp = os.path.join(d, "src.db")
        with open(srcp, "wb") as f:
            f.write(image)
        moving = _copy_side(srcp, os.path.join(d, "moving.db"),
                            table, keycol, moving_set, nslots, True)
        staying = _copy_side(srcp, os.path.join(d, "staying.db"),
                             table, keycol, moving_set, nslots, False)
        return moving, staying
    finally:
        shutil.rmtree(d, ignore_errors=True)


def fork_state_machine(sm, slots: Iterable[int], nslots: int,
                       table: str = "kv", keycol: str = "k"):
    """(applied_index, moving_image, staying_image) from a live state
    machine — the index labels BOTH forks' log position."""
    index, image = sm.serialize_with_index()
    moving, staying = fork_by_slots(image, slots, nslots, table, keycol)
    return index, moving, staying
