"""Reshard coordinator: SPLIT / MERGE / MIGRATE as resumable step machines.

One verb runs at a time (the membership plane's single-latch precedent).
The coordinator owns no durable state of its own: every step it takes is
journaled through the SOURCE group's raft log before the next step may
begin, so a coordinator SIGKILLed anywhere can be rebuilt from the
journal fold (`recover`) and either resumes the verb forward or aborts
it cleanly — it never half-applies a flip.

Step order for SPLIT (MERGE is a SPLIT of all the source's slots plus a
retire; both move slots src -> dst):

  begin   journal `begin`, freeze the moving slots (intake refused)
  drain   wait until src has APPLIED everything it committed for them
  copy    propose every moving row to dst, wait until dst APPLIED them
          — this is the durability fence the router flip waits behind
  copied  journal `copied` (the fence is now in the log)
  flip    journal `flip`; once applied, move the slots in the keymap,
          bump the epoch, publish, unfreeze — dst owns the keys
  cleanup range-delete the moved rows out of src; journal `done`

MIGRATE ships the group's snapshot image to another host dir (disk
faults abort the verb cleanly) and cuts the leader over via the
existing catch-up-gated transfer kernel; the keyspace never moves.

The coordinator talks to the world through a duck-typed backend (the
chaos runner wires it to the in-process node plane; the serving plane
wires it to RaftDB), and advances only inside `step()` — callers choose
the cadence (the chaos runner calls it once per tick for determinism,
the server runs a small thread).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from .keymap import KeyMap

VERBS = ("split", "merge", "migrate")

# Re-propose cadence for idempotent pending work (journal records,
# copies, range deletes) while a waiting state is starved — proposals
# routed at a deposed leader are simply re-issued at the next one.
RETRY_STEPS = 40

# Duration histogram bucket upper bounds, in coordinator steps.
DURATION_BUCKETS = (5, 10, 20, 50, 100, 200, 500)


class ReshardRefused(Exception):
    """A verb was rejected (one already in flight, or invalid args)."""


class ReshardCoordinator:
    """Single-verb reshard executor over an abstract backend.

    Thread model: HTTP/admin threads call `enqueue`/`doc`/`metrics_doc`
    while one driver thread (or the chaos tick loop) calls `step` —
    every mutation of coordinator state happens under `_mu`.
    """

    def __init__(self, backend, keymap: KeyMap, *,
                 num_groups: Optional[int] = None,
                 broken_flip: bool = False,
                 retry_steps: int = RETRY_STEPS,
                 clock: Optional[Callable[[], float]] = None,
                 witness_peers: tuple = ()):
        self.backend = backend
        self.keymap = keymap
        # Witness peers (config.py quorum geometry) own no shard, so a
        # migrate verb must never pick one as its destination.
        self.witness_peers = frozenset(witness_peers)
        self.num_groups = int(num_groups) if num_groups is not None \
            else len(set(keymap.slots) | keymap.retired)
        # Falsification hook: flip the router WITHOUT waiting for the
        # destination group to durably apply the copied rows.  Chaos
        # harness only — NoAckedWriteLost MUST catch this variant.
        self.broken_flip = bool(broken_flip)
        self.retry_steps = int(retry_steps)
        self._clock = clock
        self._mu = threading.Lock()
        self._cur: Optional[Dict] = None  # raftlint: guarded-by=_mu
        self._steps = 0                   # raftlint: guarded-by=_mu
        self._next_id = 1                 # raftlint: guarded-by=_mu
        self.events: List[Dict] = []      # raftlint: guarded-by=_mu
        # raftlint: guarded-by=_mu
        self.counters = {"splits": 0, "merges": 0, "migrations": 0,
                         "aborted": 0, "resumed": 0, "fork_faults": 0}
        self._durations: Dict[str, List[float]] = {v: [] for v in VERBS}

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def enqueue(self, verb: str, src: int, dst: int,
                slots=None) -> int:
        """Start a verb; returns its journal id.  Raises ReshardRefused
        while another verb is in flight or the arguments are invalid."""
        with self._mu:
            if self._cur is not None:
                raise ReshardRefused("reshard verb already in flight")
            if verb not in VERBS:
                raise ReshardRefused(f"unknown verb {verb!r}")
            src, dst = int(src), int(dst)
            if verb == "merge":
                slots = self.keymap.slots_of(src)
                if not slots:
                    raise ReshardRefused("merge source owns no slots")
            elif verb == "split":
                owned = set(self.keymap.slots_of(src))
                slots = sorted(int(s) for s in (slots or ()))
                if not slots or not set(slots) <= owned:
                    raise ReshardRefused("split slots not owned by src")
                if set(slots) == owned and dst != src:
                    verb = "merge"   # moving everything IS a merge
            else:                    # migrate: dst is a target peer
                if dst in self.witness_peers:
                    raise ReshardRefused(
                        f"peer {dst} is a witness (owns no shard); "
                        "not a migration destination")
                slots = []
            if verb != "migrate" and src == dst:
                raise ReshardRefused("src and dst are the same group")
            vid = self._next_id
            self._next_id += 1
            self._cur = {
                "id": vid, "verb": verb, "src": src, "dst": dst,
                "slots": list(slots), "state": "j:begin",
                "t_state": self._steps, "t0": self._steps,
                "wall0": self._clock() if self._clock else None,
                "rows": None, "shipped": False,
            }
            if verb != "migrate":
                self.keymap.freeze(slots)
            self._journal("begin")
            self.backend.publish(self.keymap)
            self._emit("begin")
            return vid

    # ------------------------------------------------------------------
    # recovery
    # ------------------------------------------------------------------
    def recover(self, records) -> None:
        """Rebuild router + verb state from applied journal records (the
        SIGKILL-restart path).  Folds the journal into the keymap, then
        re-enters the active verb at its last journaled step — or the
        abort path when the copy fence never made the log."""
        from .journal import fold_records
        with self._mu:
            km, active = fold_records(
                records, num_groups=self.num_groups,
                nslots=self.keymap.nslots)
            # fold_records rebuilt slot->group/epoch; graft it onto the
            # map object the router is already holding.
            self.keymap.slots = km.slots
            self.keymap.epoch = km.epoch
            self.keymap.retired = km.retired
            self.keymap.frozen = km.frozen
            ids = [0]
            for rec in records:
                if rec and "id" in rec:
                    ids.append(int(rec["id"]))
            self._next_id = max(ids) + 1
            self.backend.publish(self.keymap)
            if active is None:
                return
            steps = active["steps"]
            cur = {
                "id": active["id"], "verb": active.get("verb", "split"),
                "src": int(active.get("src", 0)),
                "dst": int(active.get("dst", 0)),
                "slots": list(active.get("slots", ())),
                "t_state": self._steps, "t0": self._steps,
                "wall0": self._clock() if self._clock else None,
                "rows": None, "shipped": "shipped" in steps,
            }
            self._cur = cur
            if cur["verb"] == "migrate":
                if "shipped" in steps:
                    cur["state"] = "cutover"   # re-drive the transfer
                else:
                    cur["state"] = "abort"     # ship not fenced: retry
            elif "flip" in steps:
                # Router flip is in the logs: finish the cleanup half
                # (re-sending the dst grant in case it never applied).
                self.backend.rdel(cur["src"], cur["slots"], cur["id"])
                self._journal_grant()
                cur["state"] = "cleanup"
            elif "copied" in steps:
                # Copy fence journaled: dst holds the rows; flip.
                self._journal("flip")
                cur["state"] = "j:flip"
            else:
                # Crashed before the copy fence: the slots may be
                # half-copied into dst.  Undo and release the freeze —
                # never guess forward past an unfenced copy.
                cur["state"] = "abort"
            self.counters["resumed"] += 1
            self._emit("resume", state=cur["state"])

    # ------------------------------------------------------------------
    # the step machine
    # ------------------------------------------------------------------
    def step(self) -> None:
        """Advance the active verb by at most one state transition.
        Non-blocking: every wait is a poll against the backend."""
        with self._mu:
            self._steps += 1
            cur = self._cur
            if cur is None:
                return
            state = cur["state"]
            handler = getattr(self, "_st_" + state.replace(":", "_"))
            handler(cur)

    def _goto(self, cur: Dict, state: str) -> None:
        cur["state"] = state
        cur["t_state"] = self._steps

    def _starved(self, cur: Dict) -> bool:
        """True once per retry window while a wait state is starved."""
        waited = self._steps - cur["t_state"]
        return waited > 0 and waited % self.retry_steps == 0

    def _journal(self, step: str) -> None:
        cur = self._cur
        rec = {"id": cur["id"], "verb": cur["verb"], "step": step,
               "src": cur["src"], "dst": cur["dst"],
               "slots": list(cur["slots"]),
               "nslots": self.keymap.nslots}
        self.backend.journal(cur["src"], rec)

    def _journal_grant(self) -> None:
        """Propose the flip record into the DESTINATION group's log too
        (fire-and-forget: the src copy is the authoritative gate).  In
        dst's own log order the grant sits after every copied row, so it
        (a) closes the verb against stale re-proposed copies and (b)
        clears dst's flipped-away fence if dst is re-acquiring slots it
        once flipped away."""
        cur = self._cur
        rec = {"id": cur["id"], "verb": cur["verb"], "step": "flip",
               "src": cur["src"], "dst": cur["dst"],
               "slots": list(cur["slots"]),
               "nslots": self.keymap.nslots}
        self.backend.journal(cur["dst"], rec, want=False)

    # raftlint: owner=driver -- only reached under _mu (step/enqueue/recover)
    def _emit(self, kind: str, **extra) -> None:
        cur = self._cur
        ev = {"kind": kind, "id": cur["id"], "verb": cur["verb"],
              "src": cur["src"], "dst": cur["dst"],
              "slots": list(cur["slots"])}
        ev.update(extra)
        self.events.append(ev)

    # -- split/merge states --------------------------------------------
    def _st_j_begin(self, cur: Dict) -> None:
        if not self.backend.journal_applied(cur["id"], "begin"):
            if self._starved(cur):
                self._journal("begin")
            return
        if cur["verb"] == "migrate":
            self._goto(cur, "ship")
        else:
            self._goto(cur, "drain")

    def _st_drain(self, cur: Dict) -> None:
        if not self.backend.drained(cur["src"], cur["slots"]):
            return
        cur["rows"] = self.backend.rows_of(cur["src"], cur["slots"])
        self.backend.copy(cur["dst"], cur["rows"])
        self._goto(cur, "copy")

    def _st_copy(self, cur: Dict) -> None:
        if not self.broken_flip:
            if not self.backend.copy_settled(cur["dst"], cur["rows"]):
                if self._starved(cur):
                    self.backend.copy(cur["dst"], cur["rows"])
                return
        # BROKEN variant falls straight through: the fence is journaled
        # before dst durably holds the rows — the premature router flip
        # NoAckedWriteLost exists to catch.
        self._journal("copied")
        self._goto(cur, "j:copied")

    def _st_j_copied(self, cur: Dict) -> None:
        if not self.backend.journal_applied(cur["id"], "copied"):
            if self._starved(cur):
                self._journal("copied")
            return
        self._journal("flip")
        self._goto(cur, "j:flip")

    def _st_j_flip(self, cur: Dict) -> None:
        if not self.backend.journal_applied(cur["id"], "flip"):
            if self._starved(cur):
                self._journal("flip")
            return
        self._flip_router(cur)
        self._journal_grant()
        self.backend.rdel(cur["src"], cur["slots"], cur["id"])
        self._emit("flip", epoch=self.keymap.epoch)
        self._goto(cur, "cleanup")

    def _flip_router(self, cur: Dict):  # raftlint: fail-closed
        """Atomically re-point the moving slots at dst and publish the
        new epoch.  Only reachable once the flip record is APPLIED in
        the source group's log — the flip exists in the same total
        order as the writes it fences."""
        self.keymap.move(cur["slots"], cur["dst"])
        self.keymap.unfreeze(cur["slots"])
        if cur["verb"] == "merge":
            try:
                self.keymap.retire(cur["src"])
            except ValueError:
                # src still owns slots — impossible while verbs are
                # serialized; publish the move, refuse the retire.
                return self.backend.publish(self.keymap)
        return self.backend.publish(self.keymap)

    def _st_cleanup(self, cur: Dict) -> None:
        if not self.backend.rdel_settled(cur["src"], cur["slots"],
                                         cur["id"]):
            if self._starved(cur):
                self.backend.rdel(cur["src"], cur["slots"], cur["id"])
                self._journal_grant()
            return
        self._journal("done")
        self._goto(cur, "j:done")

    def _st_j_done(self, cur: Dict) -> None:
        if not self.backend.journal_applied(cur["id"], "done"):
            if self._starved(cur):
                self._journal("done")
            return
        self._finish(cur, aborted=False)

    # -- migrate states ------------------------------------------------
    # raftlint: owner=driver -- only reached from step(), which holds _mu
    def _st_ship(self, cur: Dict) -> None:
        try:
            self.backend.ship(cur["src"], cur["dst"])
        except OSError:
            # Disk fault while forking/writing the snapshot image: the
            # target never saw a byte it could mistake for a shard —
            # journal the abort and leave the group where it is.
            self.counters["fork_faults"] += 1
            self._emit("fork-fault")
            self._goto(cur, "abort")
            return
        cur["shipped"] = True
        self._journal("shipped")
        self._goto(cur, "j:shipped")

    def _st_j_shipped(self, cur: Dict) -> None:
        if not self.backend.journal_applied(cur["id"], "shipped"):
            if self._starved(cur):
                self._journal("shipped")
            return
        self._goto(cur, "cutover")

    def _st_cutover(self, cur: Dict) -> None:
        outcome = self.backend.cutover(cur["src"], cur["dst"],
                                       retry=self._starved(cur))
        if outcome is None:
            return
        if outcome == "completed":
            self._journal("done")
            self._goto(cur, "j:done")
        else:
            self._goto(cur, "abort")

    # -- abort path ----------------------------------------------------
    def _st_abort(self, cur: Dict) -> None:
        if cur["verb"] != "migrate":
            # Undo any partial copies that landed in dst before the
            # crash; the rdel is idempotent and keyed by slot, and dst
            # owned none of these slots pre-flip, so it only ever
            # deletes the half-copied rows.
            self.backend.rdel(cur["dst"], cur["slots"], cur["id"])
        self._journal("abort")
        self._goto(cur, "j:abort")

    def _st_j_abort(self, cur: Dict) -> None:
        if cur["verb"] != "migrate" and not self.backend.rdel_settled(
                cur["dst"], cur["slots"], cur["id"]):
            if self._starved(cur):
                self.backend.rdel(cur["dst"], cur["slots"], cur["id"])
            return
        if not self.backend.journal_applied(cur["id"], "abort"):
            if self._starved(cur):
                self._journal("abort")
            return
        if cur["verb"] != "migrate":
            self.keymap.unfreeze(cur["slots"])
            self.backend.publish(self.keymap)
        self._finish(cur, aborted=True)

    # -- completion ----------------------------------------------------
    # raftlint: owner=driver -- only reached from step(), which holds _mu
    def _finish(self, cur: Dict, aborted: bool) -> None:
        if aborted:
            self.counters["aborted"] += 1
        else:
            key = {"split": "splits", "merge": "merges",
                   "migrate": "migrations"}[cur["verb"]]
            self.counters[key] += 1
        if self._clock and cur.get("wall0") is not None:
            dur = self._clock() - cur["wall0"]
        else:
            dur = float(self._steps - cur["t0"])
        self._durations[cur["verb"]].append(dur)
        self._emit("abort" if aborted else "done",
                   epoch=self.keymap.epoch)
        self._cur = None

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    @property
    def busy(self) -> bool:
        with self._mu:
            return self._cur is not None

    def drain_events(self) -> List[Dict]:
        with self._mu:
            evs, self.events = self.events, []
            return evs

    def doc(self) -> Dict:
        with self._mu:
            cur = None
            if self._cur is not None:
                cur = {k: self._cur[k] for k in
                       ("id", "verb", "src", "dst", "slots", "state")}
            return {"active": cur, "keymap": self.keymap.to_doc(),
                    "counters": dict(self.counters)}

    def metrics_doc(self) -> Dict:
        """Counters + per-verb duration histogram for /metrics.
        Durations are in coordinator steps unless a wall clock was
        injected, in which case they are seconds."""
        with self._mu:
            hists = {}
            for verb, durs in self._durations.items():
                buckets = {}
                for le in DURATION_BUCKETS:
                    buckets[str(le)] = sum(1 for d in durs if d <= le)
                buckets["inf"] = len(durs)
                hists[verb] = {"count": len(durs),
                               "sum": round(sum(durs), 6),
                               "bucket": buckets}
            doc = dict(self.counters)
            doc["epoch"] = self.keymap.epoch
            doc["active"] = 1 if self._cur is not None else 0
            doc["duration"] = hists
            return doc
