"""CLI entry point — the reference's server/main.go composition.

Wires proposeC→raftPipe→raftdb→HTTP exactly as the reference does
(reference server/main.go:24-38), with the TPU-native pieces underneath:

    python -m raftsql_tpu.server.main \
        --cluster http://127.0.0.1:12379,http://127.0.0.1:22379,... \
        --id 1 --port 12380

Flag parity: --cluster / --id / --port match the reference (main.go:25-27);
the DB file is `raftsql-<id>.db` (main.go:37) and the WAL dir
`raftsql-<id>` (raft.go:69).  New knobs expose the batched engine:
--groups (raft groups served by this cluster), --tick (seconds per device
step; the reference hard-codes 100ms, raft.go:207).
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
import time


def _pin_platform_from_env() -> None:
    """Make JAX_PLATFORMS effective even when a site hook captured jax
    config at interpreter startup.

    This environment's sitecustomize registers a remote-TPU ("axon") PJRT
    plugin in every python process and forces its own platform list into
    the live jax config, so the operator's JAX_PLATFORMS=cpu would
    otherwise be silently ignored — and a wedged TPU tunnel would hang
    the server at its first device computation.  Re-applying the env var
    to the live config before any device access restores the documented
    contract (same hazard + fix as tests/conftest.py)."""
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax
        jax.config.update("jax_platforms", want)

from raftsql_tpu.api.http import SQLServer
from raftsql_tpu.config import RaftConfig
from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
from raftsql_tpu.runtime.db import RaftDB
from raftsql_tpu.runtime.pipe import RaftPipe
from raftsql_tpu.transport.tcp import TcpTransport


def build_node(cluster: str, node_id: int, groups: int = 1,
               tick: float = 0.01, election_ticks: int | None = None,
               data_prefix: str = "raftsql", resume: bool = False,
               compact_every: int = 0, compact_keep: int = 1024,
               wal_segment_bytes: int = 4 << 20,
               trace: bool = False, lease_ticks: int = 0,
               max_clock_skew: int = 1,
               write_quorum: int | None = None,
               election_quorum: int | None = None,
               witnesses: tuple = ()) -> RaftDB:
    peers = cluster.split(",")
    # Default election/heartbeat timing is REAL-TIME parity with the
    # reference (~1 s election timeout, ~100 ms heartbeat at its 100 ms
    # tick — raft.go:154-155, 207), whatever the tick interval: timers
    # advance only on interval-paced steps (core/step.py timer_inc), so
    # a fast tick must mean "fine timer resolution", not "20x shorter
    # election timeout".  A 5 ms tick with the raw 10-tick default gave
    # a 50-100 ms election window — OS scheduling jitter alone fired
    # constant spurious elections under load.
    if tick > 0:
        if election_ticks is None:
            election_ticks = max(10, round(1.0 / tick))
        heartbeat_ticks = max(1, round(0.1 / tick))
        if election_ticks <= 2 * heartbeat_ticks:
            heartbeat_ticks = max(1, election_ticks // 3)
    else:
        # Untimed (tick <= 0: step-per-loop): real-time scaling is
        # meaningless — keep the reference's tick counts (raft.go:154-155).
        election_ticks = election_ticks or 10
        heartbeat_ticks = 1
    # Leader leases (config.py lease_ticks): clamp to the safe bound
    # for a rate-bounded deployment — an operator-supplied lease can
    # never exceed what the election timeout can protect.
    if lease_ticks:
        lease_ticks = min(lease_ticks,
                          max(1, election_ticks - max_clock_skew - 1))
    cfg = RaftConfig(num_groups=groups, num_peers=len(peers),
                     tick_interval_s=tick, election_ticks=election_ticks,
                     heartbeat_ticks=heartbeat_ticks,
                     wal_segment_bytes=wal_segment_bytes,
                     lease_ticks=lease_ticks,
                     max_clock_skew=max_clock_skew,
                     write_quorum=write_quorum,
                     election_quorum=election_quorum,
                     witnesses=tuple(witnesses) or None)
    transport = TcpTransport(peers, node_id - 1)
    pipe = RaftPipe.create(node_id, len(peers), cfg, transport,
                           data_dir=f"{data_prefix}-{node_id}")
    if trace:
        pipe.node.enable_tracing()

    def sm_factory(g: int) -> SQLiteStateMachine:
        path = (f"{data_prefix}-{node_id}.db" if g == 0
                else f"{data_prefix}-{node_id}-g{g}.db")
        return SQLiteStateMachine(path, resume=resume)

    return RaftDB(sm_factory, pipe, num_groups=groups, resume=resume,
                  compact_every=compact_every, compact_keep=compact_keep)


def build_fused_node(groups: int = 1, peers: int = 3,
                     tick: float = 0.002,
                     data_prefix: str = "raftsql",
                     resume: bool = False,
                     compact_every: int = 0, compact_keep: int = 1024,
                     wal_segment_bytes: int = 4 << 20,
                     trace: bool = False,
                     wal_group_commit: bool = True,
                     lease_ticks: int = 0,
                     max_clock_skew: int = 1,
                     write_quorum: int | None = None,
                     election_quorum: int | None = None,
                     witnesses: tuple = ()) -> RaftDB:
    """The --fused single-process deployment: all P peers of every
    group co-located in THIS process, consensus advanced by ONE fused
    device program per tick (runtime/fused.py), per-peer WALs on disk,
    SQLite applied from peer 0's commit stream.  The TPU-native answer
    to the reference's 3-process Procfile cluster: same durability
    (fsync-per-peer between dispatches = save-before-send), no
    cross-process hops on the propose→commit path."""
    from raftsql_tpu.runtime.fused import FusedClusterNode, FusedPipe

    # Leader leases on the fused plane: same safety clamp as
    # build_node — an operator-supplied lease can never exceed what
    # the (default) election timeout protects.
    if lease_ticks:
        election_default = RaftConfig.__dataclass_fields__[
            "election_ticks"].default
        lease_ticks = min(lease_ticks,
                          max(1, election_default - max_clock_skew - 1))
    if 0 in tuple(witnesses):
        # The fused deployment applies SQLite from peer 0's publish
        # stream (FusedPipe publish_peers={0}); a witness publishes
        # nothing, so slot 0 as witness would serve an empty database.
        raise ValueError("--fused applies from peer slot 0; pick a "
                         "different --witness slot")
    cfg = RaftConfig(num_groups=groups, num_peers=peers,
                     tick_interval_s=tick,
                     wal_segment_bytes=wal_segment_bytes,
                     lease_ticks=lease_ticks,
                     max_clock_skew=max_clock_skew,
                     write_quorum=write_quorum,
                     election_quorum=election_quorum,
                     witnesses=tuple(witnesses) or None)
    # WAL group commit is the serving default: one write+fsync per tick
    # for all P peers (storage/wal.py GroupCommitWAL).  An existing
    # per-peer data dir keeps its layout (the host plane refuses to
    # mix them); --wal-group-commit=off restores per-peer files.
    node = FusedClusterNode(cfg, f"{data_prefix}-fused",
                            group_commit=wal_group_commit)
    if trace:
        node.enable_tracing()
    node.start(interval_s=max(tick, 0.0005))
    pipe = FusedPipe(node)

    def sm_factory(g: int) -> SQLiteStateMachine:
        path = (f"{data_prefix}-fused.db" if g == 0
                else f"{data_prefix}-fused-g{g}.db")
        return SQLiteStateMachine(path, resume=resume)

    return RaftDB(sm_factory, pipe, num_groups=groups, resume=resume,
                  compact_every=compact_every, compact_keep=compact_keep)


def build_mesh_node(groups: int = 8, peers: int = 3,
                    tick: float = 0.002,
                    data_prefix: str = "raftsql",
                    group_shards: int = 0, peer_shards: int = 1,
                    resume: bool = False,
                    compact_every: int = 0, compact_keep: int = 1024,
                    wal_segment_bytes: int = 4 << 20,
                    trace: bool = False) -> RaftDB:
    """The --mesh deployment (runtime/mesh.py): the fused cluster with
    its consensus step SPMD over a real device mesh — G sharded over
    the `groups` axis — and the durable host plane sharded to match:
    per-shard WAL dirs under <prefix>-mesh/p<i>/s<j>, per-shard publish
    workers, and the SQLite state machines laid out per group shard
    under <prefix>-mesh-db/s<j>/.  `group_shards=0` auto-picks the
    widest mesh the visible devices allow (on a dev box: force
    devices with XLA_FLAGS=--xla_force_host_platform_device_count=8
    JAX_PLATFORMS=cpu)."""
    import os as _os

    from raftsql_tpu.runtime.fused import FusedPipe
    from raftsql_tpu.runtime.mesh import MeshClusterNode, MeshConfig

    cfg = RaftConfig(num_groups=groups, num_peers=peers,
                     tick_interval_s=tick,
                     wal_segment_bytes=wal_segment_bytes)
    mc = (MeshConfig.for_groups(cfg, peer_shards=peer_shards)
          if group_shards <= 0
          else MeshConfig(peer_shards=peer_shards,
                          group_shards=group_shards))
    mc.validate(cfg)
    logging.getLogger("raftsql.server").info(
        "mesh deployment: %dx%d devices, %d groups (%d per shard)",
        mc.peer_shards, mc.group_shards, groups,
        groups // mc.group_shards)
    node = MeshClusterNode(cfg, f"{data_prefix}-mesh", mc.build())
    if trace:
        node.enable_tracing()
    node.start(interval_s=max(tick, 0.0005))
    pipe = FusedPipe(node)
    g_loc = groups // mc.group_shards

    def sm_factory(g: int) -> SQLiteStateMachine:
        d = f"{data_prefix}-mesh-db/s{g // g_loc}"
        _os.makedirs(d, exist_ok=True)
        return SQLiteStateMachine(_os.path.join(d, f"g{g}.db"),
                                  resume=resume)

    return RaftDB(sm_factory, pipe, num_groups=groups, resume=resume,
                  compact_every=compact_every, compact_keep=compact_keep)


class PodRaftDB(RaftDB):
    """RaftDB over a PodClusterNode: every group-scoped verb is served
    ONLY by the group's owner host.

    Ownership is the ack-soundness boundary, not a routing nicety:
    (a) an HTTP write ack fires when the commit reaches THIS host's
    publish stream, which follows THIS host's WAL fsync — on the owner
    that is exactly "durable where the group's whole P-peer history
    lives"; on any other host it would ack a write whose only durable
    copy is still crossing the pod (the premature-ack hazard
    chaos/pod.py falsifies); (b) pending-ack matching is
    (group, query)-keyed (RaftDB._q2cb), so two hosts holding futures
    for the same group could cross-resolve each other's writes off the
    replicated publish stream.  Non-owners answer 421 + X-Raft-Leader
    naming the owner host (1-based slot in the pod hosts table) and
    the client chases, exactly like a non-leader peer in the
    multi-process deployment (api/client.py merges ownership from the
    /healthz sweep so steady state has no 421s at all)."""

    def _pod_check(self, group: int) -> None:
        node = self.pipe.node
        if not node.owns_group(int(group)):
            from raftsql_tpu.runtime.db import NotLeaderError
            raise NotLeaderError(int(group),
                                 node.group_owner(int(group)) + 1)

    def propose(self, query, group: int = 0, *a, **kw):
        self._pod_check(group)
        return super().propose(query, group, *a, **kw)

    def query(self, query, group: int = 0, *a, **kw):
        self._pod_check(group)
        return super().query(query, group, *a, **kw)

    def member_change(self, group: int, op: str, peer: int) -> dict:
        self._pod_check(group)
        return super().member_change(group, op, peer)

    def transfer(self, group: int, target: int) -> dict:
        self._pod_check(group)
        return super().transfer(group, target)


def build_pod_node(groups: int = 8, peers: int = 3, tick: float = 0.01,
                   data_prefix: str = "raftsql",
                   group_shards: int = 0,
                   pod_procs: int = 1, pod_id: int = 0,
                   pod_coord: str = "", pod_hosts: tuple = (),
                   resume: bool = False,
                   compact_every: int = 0, compact_keep: int = 1024,
                   wal_segment_bytes: int = 4 << 20,
                   trace: bool = False) -> RaftDB:
    """The --pod deployment (raftsql_tpu/pod/): N host PROCESSES
    jointly own one cluster.  Every host runs the identical replicated
    device step; the durable plane is sharded — this host materializes
    WAL dirs and SQLite files only for the group shards it OWNS
    (round-robin, pod/config.py), and the per-tick collective carries
    cross-host proposals and is the tick+fsync barrier.  Construction
    BLOCKS until all pod_procs processes join (the pod is one
    program); a lost peer is pod-wide fail-stop — the engine error
    surfaces through _watch_fatal as EXIT_CODE_FATAL, and a supervisor
    restarts the whole pod, which rebuilds from the merged cross-host
    replay.  Set RAFTSQL_POD_JAX_DISTRIBUTED=1 on real multi-host
    fleets to run the device step as one jax.distributed SPMD program
    (the dry-run default replicates it per host instead)."""
    import os as _os

    from raftsql_tpu.pod.config import PodConfig
    from raftsql_tpu.pod.node import PodClusterNode
    from raftsql_tpu.runtime.fused import FusedPipe
    from raftsql_tpu.runtime.mesh import MeshConfig

    pod = PodConfig(procs=pod_procs, proc_id=pod_id,
                    coordinator=pod_coord, hosts=tuple(pod_hosts))
    if _os.environ.get("RAFTSQL_POD_JAX_DISTRIBUTED") == "1":
        pod.init_distributed()
    cfg = RaftConfig(num_groups=groups, num_peers=peers,
                     tick_interval_s=tick,
                     wal_segment_bytes=wal_segment_bytes)
    mc = (MeshConfig.for_groups(cfg, peer_shards=1)
          if group_shards <= 0
          else MeshConfig(peer_shards=1, group_shards=group_shards))
    mc.validate(cfg)
    logging.getLogger("raftsql.server").info(
        "pod deployment: host %d/%d, %d groups over %d shards, "
        "coordinator %s", pod_id, pod.procs, groups, mc.group_shards,
        pod_coord or "(local)")
    node = PodClusterNode(pod, cfg, f"{data_prefix}-pod{pod_id}",
                          mc.build())
    if trace:
        node.enable_tracing()
    node.start(interval_s=max(tick, 0.0005))
    pipe = FusedPipe(node)
    owned = {int(g) for g in node.owned_groups()}
    db_dir = f"{data_prefix}-pod{pod_id}-db"

    def sm_factory(g: int) -> SQLiteStateMachine:
        if g not in owned:
            # Replicated compute applies every group on every host, but
            # this host is not the durable authority for g: fold into a
            # throwaway in-memory replica (keeps watermarks and status
            # truthful for /healthz) — reads and writes for g are
            # owner-served (PodRaftDB), so no file may exist here.
            return SQLiteStateMachine(":memory:", resume=False)
        _os.makedirs(db_dir, exist_ok=True)
        return SQLiteStateMachine(_os.path.join(db_dir, f"g{g}.db"),
                                  resume=resume)

    return PodRaftDB(sm_factory, pipe, num_groups=groups, resume=resume,
                     compact_every=compact_every,
                     compact_keep=compact_keep)


# Exit code when the consensus engine dies of a fatal error (failed
# fsync, injected ENOSPC, transport teardown): the etcd posture — a
# server that can no longer participate must CRASH, visibly, rather
# than keep answering HTTP with a dead engine behind it.  The chaos
# nemesis (chaos/proc.py) keys on this code.
EXIT_CODE_FATAL = 70


def _install_graceful_shutdown(rdb, srv_stop) -> None:
    """SIGTERM/SIGINT → clean stop: stop the HTTP plane (threaded or
    aio — whichever `srv_stop` closes), then close the pipe, which
    flushes and fsyncs the WAL and closes both the consensus transport
    and the SQLite state machines (RaftDB.close → RaftPipe.close →
    RaftNode.stop → WAL.close).  Exit code 0 distinguishes a clean stop
    from a crash — `kill -TERM` is "stop", SIGKILL is "crash".

    The handler only spawns a worker thread: the main thread is inside
    serve_forever(), and running a blocking shutdown inside the signal
    frame would deadlock against it.  A second signal while the first
    shutdown runs hard-exits (an operator's double Ctrl-C must win)."""
    fired = threading.Event()

    def _graceful(signum, frame):
        if fired.is_set():
            os._exit(0)
        fired.set()

        def _work():
            try:
                srv_stop()
            except Exception:                       # noqa: BLE001
                pass
            try:
                rdb.close()
            except Exception:                       # noqa: BLE001
                pass
            os._exit(0)

        # Non-daemon: when srv_stop() unblocks serve_forever and main()
        # returns, interpreter shutdown must WAIT for the WAL flush in
        # rdb.close() instead of killing it mid-write (the worker ends
        # the process itself via os._exit).
        threading.Thread(target=_work, daemon=False,
                         name="graceful-shutdown").start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)


def _watch_fatal(rdb) -> None:
    """Exit the process (EXIT_CODE_FATAL) when the consensus engine
    records a terminal error — see EXIT_CODE_FATAL above."""
    def _work():
        while True:
            if rdb.pipe.error is not None:
                logging.getLogger("raftsql.server").error(
                    "consensus engine failed, exiting: %s",
                    rdb.pipe.error)
                os._exit(EXIT_CODE_FATAL)
            time.sleep(0.2)

    threading.Thread(target=_work, daemon=True,
                     name="fatal-watch").start()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="TPU-native replicated SQL")
    ap.add_argument("--cluster", default="http://127.0.0.1:9021",
                    help="comma separated cluster peers")
    ap.add_argument("--id", type=int, default=1, help="node ID (1-based)")
    ap.add_argument("--port", type=int, default=9121,
                    help="sql server port")
    ap.add_argument("--groups", type=int, default=1,
                    help="number of raft groups")
    ap.add_argument("--tick", type=float, default=0.01,
                    help="seconds per consensus tick")
    ap.add_argument("--resume", action="store_true",
                    help="snapshot-resume: keep the SQLite file across "
                         "restarts and skip re-applying the replayed "
                         "prefix (default: reference delete-and-replay)")
    ap.add_argument("--compact-every", type=int, default=0,
                    help="with --resume: advance WAL compaction floors "
                         "(and drop covered segments) every N applies")
    ap.add_argument("--compact-keep", type=int, default=1024,
                    help="entries retained above the compaction floor "
                         "for follower catch-up")
    ap.add_argument("--wal-segment-bytes", type=int, default=4 << 20,
                    help="rotate WAL segments at this size; compaction "
                         "unlinks whole covered segments")
    ap.add_argument("--fused", action="store_true",
                    help="single-process cluster: all --peers raft "
                         "peers co-located on one device, one fused "
                         "step per tick (no --cluster/--id needed)")
    ap.add_argument("--peers", type=int, default=3,
                    help="with --fused/--mesh: peers per group")
    ap.add_argument("--mesh", action="store_true",
                    help="single-process cluster SPMD over a device "
                         "MESH (runtime/mesh.py): G sharded over the "
                         "'groups' axis, per-shard WAL dirs + publish "
                         "workers + SQLite shards (no --cluster/--id)")
    ap.add_argument("--group-shards", type=int, default=0,
                    help="with --mesh: devices on the groups axis "
                         "(0 = widest fit for the visible devices)")
    ap.add_argument("--peer-shards", type=int, default=1,
                    help="with --mesh: devices on the peers axis (the "
                         "message exchange then rides all_to_all)")
    ap.add_argument("--pod", action="store_true",
                    help="multi-host pod (raftsql_tpu/pod/): this "
                         "process is ONE of --pod-procs hosts jointly "
                         "owning the cluster — replicated device step, "
                         "durability sharded by group shard, one "
                         "cross-host collective per tick.  Boot blocks "
                         "until every host joins; a lost host is "
                         "pod-wide fail-stop (restart the whole pod)")
    ap.add_argument("--pod-procs", type=int, default=1,
                    help="with --pod: total host processes in the pod "
                         "(overridden by the length of --pod-hosts)")
    ap.add_argument("--pod-id", type=int, default=0,
                    help="with --pod: this host (0-based; 0 runs the "
                         "collective coordinator)")
    ap.add_argument("--pod-coord", default="",
                    help="with --pod: host:port the pod collective "
                         "coordinator (host 0) listens on")
    ap.add_argument("--pod-hosts", default="",
                    help="with --pod: comma separated host:port HTTP "
                         "addresses of EVERY pod host in --pod-id "
                         "order — published at /healthz so a client "
                         "pointed at one host sweeps the whole pod")
    ap.add_argument("--wal-group-commit", choices=("on", "off"),
                    default="on",
                    help="with --fused: coalesce every peer's per-tick "
                         "WAL records into ONE shared log + ONE fsync "
                         "(storage/wal.py GroupCommitWAL)")
    ap.add_argument("--workers", type=int, default=0,
                    help="N HTTP worker PROCESSES sharing this engine "
                         "through mmap propose/completion rings "
                         "(runtime/ring.py), all binding --port via "
                         "SO_REUSEPORT.  0 = serve HTTP in-process "
                         "(the classic single-process deployment)")
    ap.add_argument("--lease-ticks", type=int, default=0,
                    help="leader-lease duration in ticks (0 = off): "
                         "linearizable reads at a leader whose lease "
                         "covers now + --max-clock-skew skip the "
                         "ReadIndex quorum round.  Clamped below the "
                         "election timeout; requires bounded relative "
                         "clock rates (config.py lease_ticks)")
    ap.add_argument("--write-quorum", type=int, default=None,
                    help="flexible quorum geometry (config.py): size "
                         "of the append/commit/lease quorum; default "
                         "majority.  W + E must exceed the peer count")
    ap.add_argument("--election-quorum", type=int, default=None,
                    help="size of the vote/prevote quorum; default "
                         "majority (2E must also exceed the peer "
                         "count)")
    ap.add_argument("--witness", type=int, action="append", default=[],
                    metavar="SLOT",
                    help="0-based peer slot to run as a WITNESS: "
                         "votes, appends and fsyncs its WAL but owns "
                         "no SQLite shard and serves no reads "
                         "(repeatable)")
    ap.add_argument("--max-clock-skew", type=int, default=1,
                    help="clock-skew slack (ticks) subtracted from "
                         "every lease validity check")
    ap.add_argument("--http-engine", choices=("aio", "threaded"),
                    default="aio",
                    help="HTTP plane: single-thread event loop with "
                         "batched commit acks (aio, default) or the "
                         "thread-per-connection stdlib port (threaded)")
    ap.add_argument("--trace", action="store_true",
                    help="enable the observability planes "
                         "(raftsql_tpu/obs/): per-proposal lifecycle "
                         "spans + the on-device event ring, exported "
                         "at GET /trace (Perfetto) and GET /events")
    ap.add_argument("--placement", action="store_true",
                    help="traffic-aware leadership placement "
                         "(raftsql_tpu/placement/): a controller "
                         "thread watches the per-group traffic feed "
                         "and issues graceful leadership transfers "
                         "(POST /transfer machinery, thesis §3.10) to "
                         "balance hot groups across peers; fused/mesh "
                         "runtimes only")
    ap.add_argument("--placement-interval", type=float, default=0.5,
                    help="seconds between placement passes")
    ap.add_argument("--placement-imbalance", type=float, default=2.0,
                    help="hottest/coldest per-peer load ratio that "
                         "triggers a transfer")
    ap.add_argument("--reshard", action="store_true",
                    help="elastic keyspace (raftsql_tpu/reshard/): a "
                         "coordinator thread executes SPLIT / MERGE / "
                         "MIGRATE verbs (POST /reshard) journaled "
                         "through the raft logs, and the keyed "
                         "PUT/GET /kv/<key> surface routes by the "
                         "versioned hash-slot keymap (clients fail "
                         "closed on X-Raft-Keymap-Epoch mismatch)")
    ap.add_argument("--reshard-nslots", type=int, default=64,
                    help="hash slots in the key->group map (crc32 "
                         "%% nslots; fixed for the cluster's life)")
    ap.add_argument("--replica-listen", type=int, default=0,
                    help="publish the read-replica delta stream on "
                         "this TCP port (raftsql_tpu/replica/): "
                         "replicas subscribe with `python -m "
                         "raftsql_tpu.replica --upstream host:PORT` "
                         "and serve the read ladder remotely; 0 = off")
    ap.add_argument("--overload-cap", type=int, default=0,
                    help="bounded admission: max queued-but-unstaged "
                         "proposals per ENGINE (raftsql_tpu/overload/;"
                         " excess answers 429 + Retry-After on every "
                         "serving surface); 0 = no engine budget")
    ap.add_argument("--overload-group-cap", type=int, default=0,
                    help="bounded admission: max queued-but-unstaged "
                         "proposals per GROUP; 0 = no group budget")
    ap.add_argument("--brownout-hi", type=float, default=None,
                    help="queue-depth EWMA above which linear reads "
                         "degrade to lease-only (the brownout ladder; "
                         "default 0.75 x --overload-cap)")
    ap.add_argument("--brownout-lo", type=float, default=None,
                    help="queue-depth EWMA below which the brownout "
                         "ladder disengages (default brownout-hi / 3)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    _pin_platform_from_env()
    # Env-injected storage faults (RAFTSQL_FSIO_FAULTS): the chaos
    # nemesis's seam across the process boundary.  Installed before the
    # node boots so the very first WAL byte flows through the rules;
    # a malformed spec must kill the boot, not silently drop faults.
    from raftsql_tpu.storage import fsio
    fsio.install_from_env()
    # The serving process is ~30 cooperating threads (tick loop, HTTP
    # handlers, commit consumer, transport); CPython's default 5 ms GIL
    # switch interval makes every cross-thread handoff on the
    # propose→commit→ack path cost up to 5 ms × runnable threads.  1 ms
    # trades a little throughput for a large latency cut on small hosts.
    sys.setswitchinterval(
        float(os.environ.get("RAFTSQL_GIL_SWITCH_S", "0.001")))
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    # RAFTSQL_PROFILE=<dir>: cProfile of the consensus tick thread,
    # dumped periodically to <dir>/raftsql-node<id>-tick.prof
    # (runtime/node.py _run; SURVEY.md §5.1 — host-side profiling of
    # the serving process, the complement of the JAX profiler's device
    # traces in bench.py).
    if args.pod:
        pod_hosts = tuple(h for h in args.pod_hosts.split(",") if h)
        if (args.write_quorum is not None
                or args.election_quorum is not None or args.witness):
            ap.error("--write-quorum/--election-quorum/--witness are "
                     "not supported with --pod (the pod extends the "
                     "mesh runtime, which refuses them too)")
        if args.mesh or args.fused:
            ap.error("--pod is its own deployment; drop --mesh/--fused")
        if args.placement or args.reshard or args.workers:
            # Replicated controllers: N hosts each running a placement/
            # reshard controller would issue the same verbs N times;
            # the ring worker plane has no pod story yet.  Refuse
            # loudly rather than boot something subtly double-driven.
            ap.error("--placement/--reshard/--workers are not "
                     "supported with --pod yet")
        rdb = build_pod_node(groups=args.groups, peers=args.peers,
                             tick=args.tick,
                             group_shards=args.group_shards,
                             pod_procs=(len(pod_hosts) or args.pod_procs),
                             pod_id=args.pod_id,
                             pod_coord=args.pod_coord,
                             pod_hosts=pod_hosts,
                             resume=args.resume,
                             compact_every=args.compact_every,
                             compact_keep=args.compact_keep,
                             wal_segment_bytes=args.wal_segment_bytes,
                             trace=args.trace)
    elif args.mesh:
        if (args.write_quorum is not None
                or args.election_quorum is not None or args.witness):
            # The mesh runtime shards the GROUP axis; its geometry
            # plumbing is untested — refuse loudly rather than boot a
            # cluster whose quorums silently differ from the flags.
            ap.error("--write-quorum/--election-quorum/--witness are "
                     "not supported with --mesh (use --fused or the "
                     "multi-process deployment)")
        rdb = build_mesh_node(groups=args.groups, peers=args.peers,
                              tick=args.tick,
                              group_shards=args.group_shards,
                              peer_shards=args.peer_shards,
                              resume=args.resume,
                              compact_every=args.compact_every,
                              compact_keep=args.compact_keep,
                              wal_segment_bytes=args.wal_segment_bytes,
                              trace=args.trace)
    elif args.fused:
        rdb = build_fused_node(groups=args.groups, peers=args.peers,
                               tick=args.tick, resume=args.resume,
                               compact_every=args.compact_every,
                               compact_keep=args.compact_keep,
                               wal_segment_bytes=args.wal_segment_bytes,
                               trace=args.trace,
                               wal_group_commit=args.wal_group_commit
                               == "on",
                               lease_ticks=args.lease_ticks,
                               max_clock_skew=args.max_clock_skew,
                               write_quorum=args.write_quorum,
                               election_quorum=args.election_quorum,
                               witnesses=tuple(args.witness))
    else:
        rdb = build_node(args.cluster, args.id, groups=args.groups,
                         tick=args.tick, resume=args.resume,
                         compact_every=args.compact_every,
                         compact_keep=args.compact_keep,
                         wal_segment_bytes=args.wal_segment_bytes,
                         trace=args.trace,
                         lease_ticks=args.lease_ticks,
                         max_clock_skew=args.max_clock_skew,
                         write_quorum=args.write_quorum,
                         election_quorum=args.election_quorum,
                         witnesses=tuple(args.witness))
    _watch_fatal(rdb)
    if args.overload_cap or args.overload_group_cap \
            or args.brownout_hi is not None:
        if not (args.fused or args.mesh):
            # The admission plane guards the co-located engine's
            # propose queues; the pod and distributed deployments have
            # no overload story yet — refuse loudly rather than boot a
            # server whose knobs silently do nothing.
            ap.error("--overload-cap/--overload-group-cap/--brownout-* "
                     "require --fused or --mesh")
        from raftsql_tpu.overload import OverloadController
        rdb.pipe.node.overload = OverloadController(
            args.groups, group_cap=args.overload_group_cap,
            total_cap=args.overload_cap, seed=0,
            tick_interval_s=args.tick,
            brownout_hi=args.brownout_hi,
            brownout_lo=args.brownout_lo)
    if args.placement:
        if not (args.fused or args.mesh):
            ap.error("--placement requires --fused or --mesh (the "
                     "co-located runtimes own the traffic feed)")
        from raftsql_tpu.placement import PlacementController
        pc = PlacementController(
            rdb.pipe.node, interval_s=args.placement_interval,
            imbalance=args.placement_imbalance)
        rdb.placement = pc
        pc.start()
    if args.reshard:
        from raftsql_tpu.reshard.plane import ReshardPlane
        plane = ReshardPlane(rdb, nslots=args.reshard_nslots)
        plane.start()        # recovers the journal fold, then drives
        if rdb.placement is not None:
            # split-hottest / merge-coldest verbs ride the controller.
            rdb.placement.reshard = plane
    if args.replica_listen and args.pod:
        ap.error("--replica-listen is not supported with --pod yet "
                 "(the stream tee rides the single-engine shm "
                 "publisher)")
    if args.workers > 0:
        _serve_workers(rdb, args)    # replica plane attaches there,
        return                       # reusing the ring's shm publisher
    if args.replica_listen:
        from raftsql_tpu.replica.publisher import attach_replica_plane
        attach_replica_plane(rdb, args.replica_listen)
    if args.http_engine == "aio":
        from raftsql_tpu.api.aio import AioSQLServer
        srv = AioSQLServer(args.port, rdb)
    else:
        srv = SQLServer(args.port, rdb)
    _install_graceful_shutdown(rdb, srv.stop)
    srv.serve_forever()


def _serve_workers(rdb, args) -> None:
    """The --workers N deployment: this process runs ONLY the engine
    (consensus tick + WAL + SQLite apply) and the ring drain
    (runtime/ring.py RingServer); N child processes each run the
    asyncio HTTP plane over a RingClient, all bound to --port via
    SO_REUSEPORT.  HTTP parsing/ack serialization then spends other
    GILs, not the engine's.

    A worker that dies is respawned (it holds no state); the engine
    dying is fatal for everyone (EXIT_CODE_FATAL via _watch_fatal)."""
    import subprocess

    from raftsql_tpu.runtime.ring import RingServer

    log = logging.getLogger("raftsql.server")
    ring_dir = f"raftsql-rings-{os.getpid()}"
    ring = RingServer(rdb, ring_dir, args.workers)
    ring.start()
    if getattr(args, "replica_listen", 0):
        # The ring attached the shm publisher already; the stream tee
        # rides the same one (replica/publisher.py reuses rdb.shm).
        from raftsql_tpu.replica.publisher import attach_replica_plane
        attach_replica_plane(rdb, args.replica_listen)

    def _die_with_parent():
        # PR_SET_PDEATHSIG: a worker must not outlive its engine — a
        # SIGKILLed engine (crash, OOM) would otherwise leave orphan
        # workers serving a dead ring forever.
        try:
            import ctypes
            ctypes.CDLL("libc.so.6", use_errno=True).prctl(
                1, signal.SIGTERM)
        except OSError:                  # pragma: no cover - non-linux
            pass

    def spawn(i: int) -> "subprocess.Popen":
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        return subprocess.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.worker",
             "--rings", ring_dir, "--index", str(i),
             "--port", str(args.port)]
            + (["--trace"] if args.trace else [])
            + (["--verbose"] if args.verbose else []),
            env=env, preexec_fn=_die_with_parent)

    procs = [spawn(i) for i in range(args.workers)]
    stopping = threading.Event()

    def _stop_all():
        stopping.set()
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=5)
            except Exception:                           # noqa: BLE001
                p.kill()
        ring.stop()

    _install_graceful_shutdown(rdb, _stop_all)
    log.info("engine up; %d HTTP workers on port %d (rings in %s)",
             args.workers, args.port, ring_dir)
    while True:
        for i, p in enumerate(procs):
            rc = p.poll()
            if rc is not None and not stopping.is_set():
                log.warning("worker %d exited rc=%s; respawning", i, rc)
                procs[i] = spawn(i)
        time.sleep(0.5)


if __name__ == "__main__":
    main()
