"""HTTP worker process for the multi-worker serving plane.

Spawned by `server/main.py --workers N` (one process per worker): runs
the asyncio HTTP plane (api/aio.py) over a `RingClient` facade
(runtime/ring.py) instead of an in-process RaftDB — every proposal
becomes a record in this worker's mmap'd propose ring, every ack a
completion-ring record resolved into the event loop.  All N workers
bind the SAME port with SO_REUSEPORT; the kernel spreads connections.

The worker holds no consensus, storage, or SQLite state: it can be
killed and respawned freely (in-flight requests on its connections
fail; the engine's retry-token dedup keeps client-side retries
exactly-once).  It exits when its parent's rings disappear or on
SIGTERM.
"""
from __future__ import annotations

import argparse
import logging
import os
import signal


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="raftsql HTTP ring worker")
    ap.add_argument("--rings", required=True,
                    help="ring directory created by the engine process")
    ap.add_argument("--index", type=int, required=True,
                    help="worker index (selects the ring pair)")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--timeout", type=float, default=30.0)
    ap.add_argument("--trace", action="store_true",
                    help="stamp each ring round trip into a per-process "
                         "trace segment (pid/worker-id tagged) the "
                         "engine's /trace merges into one Perfetto "
                         "timeline")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s worker%(process)d %(levelname)s %(message)s")

    # The worker never touches a device — pin the cpu backend before
    # anything imports jax so a wedged accelerator tunnel cannot hang
    # HTTP serving (same hazard as server/main.py _pin_platform).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from raftsql_tpu.api.aio import AioSQLServer
    from raftsql_tpu.runtime.ring import RingClient

    rdb = RingClient(args.rings, args.index, trace=args.trace)
    srv = AioSQLServer(args.port, rdb, timeout_s=args.timeout,
                       reuse_port=True)

    def _term(signum, frame):
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    try:
        srv.serve_forever()
    finally:
        rdb.close()


if __name__ == "__main__":
    main()
