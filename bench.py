"""Benchmark harness — the five BASELINE.json configs on one chip.

Headline (default, what the driver records): committed log entries per
second across N raft groups, using the fused whole-cluster step
(core/cluster.py) — P peers x G groups advanced per device tick, proposals
flowing at the flow-control limit, commits counted on device so only one
scalar crosses the host boundary per timed run.

Latency is MEASURED, not estimated: the commit trajectory [T, G] is kept on
device, `ops.commit_scan.commit_latency_ticks` finds the first tick at
which each group commits the batch appended on tick 0, and p50/p99 ticks x
measured tick wall-time give propose→commit milliseconds (stderr + README).
Groups that never commit the target inside the run are excluded from the
percentiles and reported as a censored count.

Prints exactly one JSON line on stdout and ALWAYS exits 0:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "platform": ...}

Robustness model (round 1 died on backend init, round 2 on one monolithic
G=100k attempt): the process runs as a PARENT that never imports a jax
backend.  Every measurement is a CHILD subprocess under a hard timeout.
The parent first PROBES the default platform with a short timeout (a
wedged remote-TPU tunnel hangs device init indefinitely), then — if the
probe says tpu — runs a single-shape G-ladder (1k → 10k → 32k → 100k)
smallest-first with per-shape fault capture and a second pass over failed
shapes, keeping the BEST-value rung as the headline.  Then, in budget
priority order: a durable-path child (real RaftNode cluster: WAL + KV
apply + loopback transport, on cpu), a latency child (G=1024/E=16, the
<2 ms p50 shape), and the commit-rule race.  A cpu headline is the
last-resort fallback.  Exit code is ALWAYS 0 with one JSON line on
stdout.

The reference (chzchzchz/raftsql) publishes no numbers (BASELINE.md); the
baseline used for `vs_baseline` is the driver-set north star of 1e8
commits/sec (100k groups x 1k proposals/sec each, BASELINE.json).

Environment knobs:
  BENCH_CONFIG   headline | quorum | elections | commit_scan | multichip
                 | rules | latency | durable | georeads | all
                 (default headline)
  BENCH_GEO_SECONDS / BENCH_GEO_RTT_MS / BENCH_GEO_THINK_MS
                 georeads rung length, injected upstream RTT and the
                 closed-loop client think time (defaults 5s, 60, 50)
  BENCH_GROUPS / BENCH_PEERS / BENCH_TICKS / BENCH_REPEATS
  BENCH_E        append batch size (headline default 32; latency sweeps
                 pin 16 via BENCH_LAT_E; BENCH_LAT_GROUPS sets their G)
  BENCH_LADDER   comma-separated group counts
                 (default 1000,10000,32768,100000)
  BENCH_DURABLE_ACTIVE  N groups carrying load in the durable bench
  BENCH_PLATFORM cpu|tpu        (parent: single attempt on this platform)
  BENCH_ATTEMPT_TIMEOUT_S       (default 420, per child attempt)
  BENCH_PROBE_TIMEOUT_S         (default 150, platform probe)
  BENCH_TOTAL_BUDGET_S          (default 1800, whole-parent wall budget)
  BENCH_SKIP_DURABLE=1 / BENCH_SKIP_SWEEP=1 / BENCH_SKIP_RULES=1
  BENCH_PROFILE  <dir>          (wrap timed runs in jax.profiler.trace)
  BENCH_POD_PROCS=N  with BENCH_CONFIG=multichip: add the multi-host
                 pod rung — N real `pod.dryrun --mode bench` processes
                 over the TCP collective, reporting commits/s plus the
                 per-host cross-host hop cost (pod_wait_ms_per_tick)
                 next to the phase shares (BENCH_POD_TICKS overrides)
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import subprocess
import sys
import threading
import time

NORTH_STAR_COMMITS_PER_SEC = 1.0e8

# Committed ledger of every successful on-device measurement (VERDICT r3
# task 1): the round-3 TPU evidence survived only in a gitignored stray
# stderr log while the official JSON recorded a CPU fallback, because
# the tunnel wedged between the real run and the driver's capture.
# Every TPU child now appends its JSON line (+ timestamp, git SHA,
# shape) here, and the parent's CPU-fallback JSON carries the newest
# ledger entry as `last_good_tpu` — the headline stays honest (CPU),
# but the history stops being erasable.
TPU_RUNS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "TPU_RUNS.jsonl")


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _git_sha() -> str:
    try:
        r = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                           text=True, timeout=10)
        return r.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _ledger_append(record: dict) -> None:
    """Append one run record to TPU_RUNS.jsonl (best-effort: a read-only
    checkout must not fail the measurement that produced the record)."""
    try:
        with open(TPU_RUNS_PATH, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
    except OSError as e:                       # pragma: no cover
        _log(f"bench: ledger append failed: {e}")


def _ledger_last_matching(shape: dict) -> dict | None:
    """Newest TPU-platform ledger entry whose (config, groups, e)
    matches — the comparison point for the >20%-drop regression
    tripwire.  Matching is on the reported platform ("tpu"), not the
    raw backend name: the axon tunnel and a direct TPU VM drive the
    same chip and their numbers are the same series."""
    try:
        with open(TPU_RUNS_PATH) as f:
            lines = f.read().strip().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict) or rec.get("platform") != "tpu":
            continue
        if all(rec.get(k, "") == v for k, v in shape.items()):
            return rec
    return None


def _ledger_last_good() -> dict | None:
    """Newest TPU entry from the committed ledger, or None."""
    return _ledger_last_matching({})


# ---------------------------------------------------------------------------
# Child: one measurement attempt on one platform.
# ---------------------------------------------------------------------------


def _profiled():
    import jax
    d = os.environ.get("BENCH_PROFILE")
    return jax.profiler.trace(d) if d else contextlib.nullcontext()


def make_bench_run(cfg, num_ticks: int):
    """Jitted: scan `num_ticks` cluster ticks; returns device scalars
    (commit delta, [p50, p99] latency ticks, number of groups that
    committed the tick-0 batch).

    Latency: the proposals appended during tick 0 of the run define a
    per-group target index (max log_len after tick 0); the commit
    trajectory's first crossing of that target is the measured
    propose→commit tick count (ops/commit_scan.py).  Groups that never
    cross inside the run are right-censored: excluded from percentiles,
    counted separately.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.core.cluster import cluster_step
    from raftsql_tpu.ops.commit_scan import (commit_latency_ticks,
                                             running_commit)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(states, inboxes, prop_n):
        commit0 = jnp.max(states.commit, axis=0)                    # [G]

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop_n)
            return (st, ib), (jnp.max(st.commit, axis=0),
                              jnp.max(st.log_len, axis=0))

        (states, inboxes), (ctraj, ltraj) = jax.lax.scan(
            body, (states, inboxes), None, length=num_ticks)
        committed = jnp.sum(ctraj[-1] - commit0)
        first = commit_latency_ticks(running_commit(ctraj), ltraj[0])
        ok = first < num_ticks                                      # [G]
        n_ok = jnp.sum(ok)
        lats = jnp.sort(jnp.where(ok, (first + 1).astype(jnp.float32),
                                  jnp.inf))
        G = lats.shape[0]

        def q(p):
            i = (p * (n_ok.astype(jnp.float32) - 1.0)).astype(jnp.int32)
            return lats[jnp.clip(i, 0, G - 1)]

        pct = jnp.where(n_ok > 0, jnp.stack([q(0.5), q(0.99)]),
                        jnp.full((2,), jnp.inf))
        return states, inboxes, committed, pct, n_ok

    return run


def bench_throughput(groups: int, peers: int, ticks: int, repeats: int,
                     load: int | None = None, commit_rule: str = "point",
                     stats: dict | None = None, e: int | None = None):
    """Commits/sec + measured latency for a G x P fused cluster.

    `load` = proposals submitted per group per tick (None = saturating,
    i.e. max_entries_per_msg).  `e` = append batch size override
    (default env BENCH_E, else 32: throughput is G x E per tick and the
    measured TPU sweep gives E=32 +55% over E=16 at ~1.7 ms/tick, while
    E=16 keeps the tick at 0.3-0.5 ms — the latency sweep pins it).
    Returns best commits/s; if `stats` is given, records {"p50_ms",
    "p99_ms", "tick_ms"} of the best repeat.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core.cluster import (empty_cluster_inbox,
                                          init_cluster_state)

    # With pipelined replication throughput is G x E per tick; the
    # measured TPU sweep (README) picks E=32/W=256 for throughput runs
    # and E=16/W=128 for latency runs.
    E = e if e is not None else int(os.environ.get("BENCH_E", "32"))
    cfg = RaftConfig(num_groups=groups, num_peers=peers,
                     log_window=max(8 * E, 64), max_entries_per_msg=E,
                     tick_interval_s=0.0, commit_rule=commit_rule,
                     # The windowed/pallas rules scan the [G, W] term
                     # ring; the point rule reads only the transition
                     # table, so the ring (write fills ~40% of the
                     # remaining tick) is dropped.
                     keep_ring=commit_rule != "point")
    # Build the initial state ON device in one compiled program — at 100k
    # groups the eager per-leaf host->device transfers are the slow (and,
    # through a remote-device tunnel, fragile) path.
    states, inboxes = jax.jit(
        lambda: (init_cluster_state(cfg), empty_cluster_inbox(cfg)))()
    saturate = load is None
    load = cfg.max_entries_per_msg if saturate else min(
        load, cfg.max_entries_per_msg)
    full = jnp.full((cfg.num_peers, cfg.num_groups), load, jnp.int32)

    run = make_bench_run(cfg, ticks)

    # Warmup (elect leaders everywhere) reuses the RUN program at zero
    # load — a separate shorter-scan warmup program would cost a second
    # full compile, which on the remote-TPU tunnel can dominate the
    # child's time budget.  Repeat for short runs so every group gets at
    # least ~4 election intervals to settle.
    for _ in range(max(1, -(-4 * cfg.election_ticks // ticks))):
        states, inboxes, _, _, _ = run(states, inboxes, full * 0)
    states, inboxes, c, _, _ = run(states, inboxes, full)
    jax.block_until_ready(c)

    best, best_p50, best_p99, best_tick = 0.0, float("inf"), float("inf"), 0.0
    total_committed = 0
    repeat_rates: list = []
    label = "saturated" if saturate else f"load={load}/group/tick"
    for _ in range(repeats):
        t0 = time.perf_counter()
        with _profiled():
            states, inboxes, committed, pct, n_ok = run(
                states, inboxes, full)
            committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        total_committed += committed
        rate = committed / dt
        tick_ms = dt / ticks * 1e3
        n_ok = int(n_ok)
        if n_ok:
            p50, p99 = float(pct[0]) * tick_ms, float(pct[1]) * tick_ms
            lat_msg = (f"measured propose->commit p50={p50:.3f} ms "
                       f"p99={p99:.3f} ms ({float(pct[0]):.0f}/"
                       f"{float(pct[1]):.0f} ticks x {tick_ms:.4f} ms/tick, "
                       f"{groups - n_ok} censored)")
            if p50 < best_p50:
                best_p50, best_p99, best_tick = p50, p99, tick_ms
        else:
            lat_msg = "latency n/a (no group committed the marked batch)"
        _log(f"  {committed} commits in {dt:.3f}s -> {rate:,.0f} commits/s "
             f"({rate / groups:,.1f}/group/s); {lat_msg}")
        best = max(best, rate)
        repeat_rates.append(round(rate, 1))
    if saturate and total_committed == 0:
        raise RuntimeError("benchmark committed nothing — engine stalled")
    if best_p50 < float("inf"):
        _log(f"  best: {best:,.0f} commits/s, measured propose->commit "
             f"p50={best_p50:.3f} ms p99={best_p99:.3f} ms ({label})")
    if stats is not None:
        # None, not inf: json.dumps would emit the non-RFC token
        # `Infinity` and break strict parsers of the one-JSON-line
        # contract exactly on the degenerate (nothing committed) run.
        got_lat = best_p50 < float("inf")
        stats["p50_ms"] = round(best_p50, 3) if got_lat else None
        stats["p99_ms"] = round(best_p99, 3) if got_lat else None
        stats["tick_ms"] = round(best_tick, 4) if got_lat else None
        stats["repeat_rates"] = repeat_rates
        if len(repeat_rates) > 1 and max(repeat_rates) > 0:
            stats["repeat_spread"] = round(
                (max(repeat_rates) - min(repeat_rates))
                / max(repeat_rates), 3)
    return best


def _light_row(sweep: dict) -> dict:
    """The light-load row of a latency sweep (labels carry a G suffix)."""
    return next((v for k, v in sweep.items() if k.startswith("light_1")),
                {})


def bench_reads(peers: int = 3, seconds: float = 2.0) -> tuple:
    """BENCH_CONFIG=reads: the read-plane ladder on the DISTRIBUTED
    runtime (3 RaftNodes over loopback — the plane where a ReadIndex
    round actually costs a quorum round trip, unlike the co-located
    fused cluster where leadership is process-local):

      local       stale local read (reference parity)
      lease       linearizable via the leader lease (no quorum round)
      read_index  linearizable via the full ReadIndex round
      session     watermark read at the leader (applied >= wm)
      follower    replicated-watermark read at a follower

    Headline = lease reads/s (the optimization under test); the whole
    ladder rides the extras.  One serial client — this measures
    per-read PATH cost, not parallel throughput."""
    import tempfile

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.pipe import RaftPipe
    from raftsql_tpu.transport.loopback import (LoopbackHub,
                                                LoopbackTransport)

    cfg = RaftConfig(num_groups=1, num_peers=peers,
                     tick_interval_s=0.0005, election_ticks=40,
                     heartbeat_ticks=4, log_window=64,
                     max_entries_per_msg=8,
                     lease_ticks=20, max_clock_skew=2)
    rates: dict = {}
    with tempfile.TemporaryDirectory(prefix="raftsql-bench-reads-") as d:
        hub = LoopbackHub()
        dbs = []
        for i in range(peers):
            pipe = RaftPipe.create(
                i + 1, peers, cfg, LoopbackTransport(hub),
                data_dir=os.path.join(d, f"raftsql-{i + 1}"))
            dbs.append(RaftDB(
                lambda g, i=i: SQLiteStateMachine(
                    os.path.join(d, f"db-{i}.db")),
                pipe, num_groups=1))
        try:
            assert dbs[0].propose(
                "CREATE TABLE t (v text)").wait(30.0) is None
            assert dbs[0].propose(
                "INSERT INTO t (v) VALUES ('x')").wait(30.0) is None
            deadline = time.monotonic() + 30.0
            lead = None
            while lead is None and time.monotonic() < deadline:
                lead = next((i for i, db in enumerate(dbs)
                             if db.pipe.node._last_role[0] == 2), None)
                if lead is None:
                    time.sleep(0.02)
            if lead is None:
                raise RuntimeError("no leader elected")
            ldb = dbs[lead]
            fdb = dbs[(lead + 1) % peers]
            sel = "SELECT count(*) FROM t"
            wm = ldb.watermark(0)

            def timed(fn) -> float:
                fn()                      # warm (lease round, caches)
                n = 0
                t0 = time.monotonic()
                while time.monotonic() - t0 < seconds:
                    fn()
                    n += 1
                return n / (time.monotonic() - t0)

            rates["local"] = round(timed(lambda: ldb.query(sel)), 1)
            rates["lease"] = round(timed(
                lambda: ldb.query(sel, mode="linear")), 1)
            # Same path with the lease fast path disabled (the seam the
            # engine itself uses when cfg.lease_ticks == 0): every read
            # pays the full quorum round.
            node = ldb.pipe.node
            saved = node.lease_read
            node.lease_read = lambda g: None
            try:
                rates["read_index"] = round(timed(
                    lambda: ldb.query(sel, mode="linear")), 1)
            finally:
                node.lease_read = saved
            rates["session"] = round(timed(
                lambda: ldb.query(sel, mode="session", watermark=wm)),
                1)
            rates["follower"] = round(timed(
                lambda: fdb.query(sel, mode="follower")), 1)

            # --- PR 12 rung: batched ReadIndex under concurrency.
            # Every pending linear read of a tick shares ONE quorum
            # round (runtime/node.py read_join; lease disabled so each
            # read takes the §6.4 path) — the serial read_index rung
            # above is the before number.
            node.lease_read = lambda g: None
            try:
                nthreads = 128
                counts = [0] * nthreads
                stop_at = time.monotonic() + seconds

                def rloop(i: int) -> None:
                    while time.monotonic() < stop_at:
                        ldb.query(sel, mode="linear")
                        counts[i] += 1
                threads = [threading.Thread(target=rloop, args=(i,),
                                            daemon=True)
                           for i in range(nthreads)]
                t0 = time.monotonic()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                dt = time.monotonic() - t0
                rates["read_index_mt128"] = round(sum(counts) / dt, 1)
            finally:
                node.lease_read = saved

            # --- PR 12 rungs: the shm worker plane vs the ring.  One
            # RingServer over the leader's RaftDB, two worker slots:
            # slot 0 maps the shared-memory snapshot (zero-round-trip
            # fast path), slot 1 runs with the plane off so every GET
            # pays the full ring round trip — same engine, same query,
            # the pair is the before/after of runtime/shm.py.
            from raftsql_tpu.runtime.ring import RingClient, RingServer
            ring = RingServer(ldb, os.path.join(d, "rings"), 2)
            ring.start()
            shm_c = ring_c = None
            try:
                shm_c = RingClient(os.path.join(d, "rings"), 0)
                os.environ["RAFTSQL_SHM_READS"] = "0"
                try:
                    ring_c = RingClient(os.path.join(d, "rings"), 1)
                finally:
                    del os.environ["RAFTSQL_SHM_READS"]
                rates["ring_local"] = round(timed(
                    lambda: ring_c.query(sel)), 1)
                rates["shm_local"] = round(timed(
                    lambda: shm_c.query(sel)), 1)
                rates["ring_session"] = round(timed(
                    lambda: ring_c.query(sel, mode="session",
                                         watermark=wm)), 1)
                rates["shm_session"] = round(timed(
                    lambda: shm_c.query(sel, mode="session",
                                        watermark=wm)), 1)
                rates["shm_linear"] = round(timed(
                    lambda: shm_c.query(sel, mode="linear")), 1)
                shm_stats = {"shm_hits": shm_c._shm_hits,
                             "shm_fallbacks": shm_c._shm_fallbacks}
            finally:
                for c in (shm_c, ring_c):
                    if c is not None:
                        c.close()
                ring.stop()

            m = node.metrics
            extras = {"reads_ladder": rates,
                      "lease_grants": m.lease_grants,
                      "lease_expiries": m.lease_expiries,
                      "lease_degrades": m.lease_degrades,
                      "read_index_batched": m.reads_read_index_batched,
                      "read_batch_hist": dict(m.read_batch_hist)}
            extras.update(shm_stats)
            _log(f"reads ladder: {rates}")
            return float(rates["lease"]), extras
        finally:
            for db in dbs:
                try:
                    db.close()
                except Exception:                   # noqa: BLE001
                    pass


def bench_latency_sweep(groups: int, peers: int, repeats: int) -> dict:
    """Propose→commit latency at light / half / saturating load.

    VERDICT r2 task 3: the <2ms p50 target (BASELINE.md) is a latency
    target, and a saturated-only benchmark measures queueing, not the
    engine floor.  Reports {load_label: {p50_ms, p99_ms, tick_ms}}.
    """
    sweep = {}
    # Long scans so tick_ms reflects the DEVICE tick cadence (the
    # tunnel's ~70 ms per-execution dispatch would otherwise inflate a
    # 32-tick call's apparent tick time ~5x); the commit crossing still
    # lands in the first few ticks and p50 = crossing_ticks x tick_ms.
    ticks = 256
    # Latency is a best-case target (<2 ms p50, BASELINE.md): measure at
    # a modest group count where the tick is fastest, and again at the
    # headline shape so the queueing story at scale is also on record.
    lat_groups = min(groups, int(os.environ.get("BENCH_LAT_GROUPS", "1024")))
    # BENCH_LAT_E > BENCH_E > 16: an explicitly-set BENCH_E still governs
    # the sweep (small-machine runs set it); only the *default* differs
    # from the headline's (which favors E=32 throughput).
    E = int(os.environ.get("BENCH_LAT_E",
                           os.environ.get("BENCH_E", "16")))
    for label, load in ((f"light_1_G{lat_groups}", 1),
                        (f"sat_{E}_G{lat_groups}", None),
                        (f"sat_{E}_G{groups}", "headline")):
        g = groups if load == "headline" else lat_groups
        ld = None if load in (None, "headline") else load
        if load == "headline" and groups == lat_groups:
            continue        # same shape as the sat_G{lat_groups} row
        _log(f"== latency @ {label} ==")
        st: dict = {}
        bench_throughput(g, peers, ticks, repeats, load=ld, stats=st, e=E)
        sweep[label] = st
    # p50-vs-G curve (VERDICT r4 task 4): sustained (saturating) load at
    # each rung of BENCH_LAT_CURVE — the scaling story for the <2 ms
    # target, not just one shape.  Off by default on cpu fallbacks
    # (costly); the parent's latency child turns it on for the device.
    curve_spec = os.environ.get("BENCH_LAT_CURVE", "")
    if curve_spec:
        curve = {}
        for g in (int(x) for x in curve_spec.split(",") if x):
            st = {}
            _log(f"== latency curve @ G={g} (sat, E={E}) ==")
            bench_throughput(g, peers, ticks, repeats, stats=st, e=E)
            curve[str(g)] = {k: st.get(k)
                             for k in ("p50_ms", "p99_ms", "tick_ms")}
        sweep["p50_vs_G"] = curve
    return sweep


def bench_elections(groups: int, peers: int, repeats: int) -> float:
    """BASELINE config 3: randomized leader election at G x P.

    Measures cold-start elections/sec: from a fresh (all-follower) state,
    ticks until every group has a leader, repeated; value = groups elected
    per second of device time.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import LEADER, RaftConfig
    from raftsql_tpu.core.cluster import (cluster_step, empty_cluster_inbox,
                                          init_cluster_state)

    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    T = 4 * cfg.election_ticks

    @jax.jit
    def elect(seed):
        states = init_cluster_state(cfg, seed=0)
        # Re-randomize timers per repeat by folding the seed into rng.
        states = states._replace(tick=states.tick + seed)
        inboxes = empty_cluster_inbox(cfg)
        prop = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop)
            return (st, ib), None

        (states, _), _ = jax.lax.scan(body, (states, inboxes), None,
                                      length=T)
        return jnp.sum(jnp.any(states.role == LEADER, axis=0))

    elected = int(elect(jnp.asarray(0, jnp.int32)))  # compile + check
    best = 0.0
    for r in range(repeats):
        t0 = time.perf_counter()
        elected = int(jax.block_until_ready(elect(jnp.asarray(r, jnp.int32))))
        dt = time.perf_counter() - t0
        _log(f"  elected {elected}/{groups} leaders in {dt:.3f}s "
             f"({T} ticks) -> {elected / dt:,.0f} elections/s")
        best = max(best, elected / dt)
    return best


def bench_commit_scan(groups: int, repeats: int) -> float:
    """BASELINE config 4: the commit-index kernel alone at 100k groups.

    Measures group-commit-scans/sec of `windowed_commit_index` (the full
    masked prefix scan over the term ring) on random match/ring state.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.ops.commit_scan import windowed_commit_index

    W, P = 64, 5
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    log_len = jax.random.randint(ks[0], (groups,), 0, W, dtype=jnp.int32)
    match = jnp.minimum(
        jax.random.randint(ks[1], (groups, P), 0, W, dtype=jnp.int32),
        log_len[:, None])
    log_term = jax.random.randint(ks[2], (groups, W), 1, 4, dtype=jnp.int32)
    commit = jnp.maximum(log_len - 8, 0)
    term = jnp.full((groups,), 3, jnp.int32)
    is_leader = jnp.ones((groups,), bool)

    @jax.jit
    def kernel(match, log_term, log_len, commit, term):
        return windowed_commit_index(match, log_term, log_len, commit,
                                     term, is_leader, quorum=3, window=W)

    out = jax.block_until_ready(
        kernel(match, log_term, log_len, commit, term))
    iters = 50
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = kernel(match, log_term, log_len, commit, term)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rate = groups * iters / dt
        _log(f"  {iters} x {groups}-group commit scans in {dt:.3f}s -> "
             f"{rate:,.0f} scans/s")
        best = max(best, rate)
    return best


def bench_multichip(ticks: int, repeats: int,
                    groups: int | None = None) -> float:
    """BASELINE config 5: groups sharded over the device mesh, peer
    message exchange riding `all_to_all` (parallel/sharded.py).
    `groups` overrides the shape for the G-scale ladder rungs."""
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core.cluster import (empty_cluster_inbox,
                                          init_cluster_state)
    from raftsql_tpu.parallel.sharded import (make_mesh,
                                              make_sharded_cluster_run,
                                              shard_cluster_arrays)

    n = len(jax.devices())
    pp = 2 if n % 2 == 0 and n > 1 else 1
    gg = n // pp
    if groups is None:
        groups = int(os.environ.get("BENCH_GROUPS", 8192 * gg))
    groups -= groups % gg
    cfg = RaftConfig(num_groups=groups, num_peers=2 * pp if pp > 1 else 3,
                     log_window=64, max_entries_per_msg=8,
                     tick_interval_s=0.0)
    mesh = make_mesh(pp, gg)
    _log(f"  mesh {pp}x{gg} over {n} devices, {groups} groups x "
         f"{cfg.num_peers} peers")
    states = init_cluster_state(cfg)
    inboxes = empty_cluster_inbox(cfg)
    full = jnp.full((ticks, cfg.num_peers, cfg.num_groups),
                    cfg.max_entries_per_msg, jnp.int32)
    states, inboxes = shard_cluster_arrays(mesh, states, inboxes)

    run = make_sharded_cluster_run(cfg, mesh, ticks)
    states, inboxes, c = run(states, inboxes, full * 0)   # warmup/elect
    states, inboxes, c = run(states, inboxes, full)
    jax.block_until_ready(c)

    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        states, inboxes, committed = run(states, inboxes, full)
        committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        _log(f"  {committed} commits in {dt:.3f}s -> "
             f"{committed / dt:,.0f} commits/s")
        best = max(best, committed / dt)
    return best


def bench_pod_rung(procs: int, ticks: int) -> dict:
    """BENCH_POD_PROCS=N rung of BENCH_CONFIG=multichip: N real
    `raftsql_tpu.pod.dryrun --mode bench` processes form a pod on this
    box (the dry-run rung — each process replicates the device step on
    forced host CPU devices; the sharded durability and the per-tick
    TCP collective are real).  Throughput is host 0's commits/s —
    compute is replicated, so hosts don't sum — and pod_wait_ms_per_tick
    is the CROSS-HOST HOP COST: collective wait per tick, reported
    per host next to the device/durable phase shares, so the profile
    attributes what the pod barrier adds at N hosts."""
    import json as _json
    import shutil
    import socket as _socket
    import subprocess
    import sys as _sys
    import tempfile

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    tmp = tempfile.mkdtemp(prefix="bench-pod-")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    ticks = int(os.environ.get("BENCH_POD_TICKS", str(max(ticks, 60))))
    outs = [os.path.join(tmp, f"h{i}.json") for i in range(procs)]
    try:
        children = [subprocess.Popen(
            [_sys.executable, "-m", "raftsql_tpu.pod.dryrun",
             "--mode", "bench", "--procs", str(procs),
             "--proc-id", str(i),
             "--coord", coord if procs > 1 else "",
             "--data-dir", os.path.join(tmp, f"h{i}"),
             "--ticks", str(ticks), "--out", outs[i]],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL) for i in range(procs)]
        for c in children:
            c.wait(timeout=600)
        docs = []
        for i, (c, o) in enumerate(zip(children, outs)):
            if c.returncode != 0 or not os.path.exists(o):
                return {"procs": procs,
                        "error": f"host {i} rc={c.returncode}"}
            with open(o, encoding="utf-8") as f:
                docs.append(_json.load(f))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    d0 = docs[0]
    rung = {"procs": procs, "ticks": ticks,
            "commits_per_s": d0["commits_per_s"],
            "pod_wait_ms_per_tick": [d["pod_wait_ms_per_tick"]
                                     for d in docs],
            "phase_ms_per_tick": d0["phase_ms_per_tick"],
            "bytes_tx": sum(d["pod"]["bytes_tx"] for d in docs)}
    if "phase_shares" in d0:
        rung["phase_shares"] = d0["phase_shares"]
    _log(f"  pod rung: {procs} hosts, {d0['commits_per_s']:,.0f} "
         f"commits/s, gather wait {rung['pod_wait_ms_per_tick']} ms/tick")
    return rung


def bench_durable(groups: int, peers: int, ticks: int, repeats: int):
    """The DURABLE product path: a real in-process RaftNode cluster —
    WAL fsync before send before publish (reference raft.go:227-235),
    loopback transport, KV apply — manually ticked in lockstep.

    VERDICT r2 task 2: the device-only headline skips the host runtime;
    this config measures what a user of the full framework gets.  Load
    is pre-queued (E per group per tick) so the feeder isn't timed.
    """
    import shutil
    import tempfile

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.kv_sm import KVStateMachine
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.node import RaftNode
    from raftsql_tpu.transport.loopback import LoopbackHub, LoopbackTransport

    E = 8
    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=E, tick_interval_s=0.0)
    tmp = tempfile.mkdtemp(prefix="bench-durable-")
    # BENCH_TRANSPORT=tcp: peer traffic rides real localhost sockets
    # through the binary codec — the DCN product path — instead of the
    # in-process loopback.
    if os.environ.get("BENCH_TRANSPORT") == "tcp":
        import socket as _socket

        from raftsql_tpu.transport.tcp import TcpTransport
        socks, urls = [], []
        for _ in range(peers):
            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            urls.append(f"http://127.0.0.1:{s.getsockname()[1]}")
        for s in socks:
            s.close()
        transports = [TcpTransport(urls, i) for i in range(peers)]
    else:
        hub = LoopbackHub(codec=False)
        transports = [LoopbackTransport(hub) for _ in range(peers)]
    nodes = [RaftNode(i + 1, peers, cfg, transports[i],
                      os.path.join(tmp, f"n{i + 1}")) for i in range(peers)]
    # BENCH_SM=sqlite: the reference-parity apply engine (one SQLite
    # database per group, group-committed) instead of the in-memory KV —
    # the number then covers the FULL product stack.
    sm_kind = os.environ.get("BENCH_SM", "kv")
    if sm_kind == "sqlite":
        sms = [SQLiteStateMachine(os.path.join(tmp, f"sm-{g}.db"))
               for g in range(groups)]
        for g, sm in enumerate(sms):
            err = sm.apply("CREATE TABLE t (v text)", 0)
            assert err is None, err
        mk_cmd = "INSERT INTO t (v) VALUES ('x')"
    else:
        sms = [KVStateMachine() for _ in range(groups)]
        mk_cmd = "SET k v"

    def drain(n0: "RaftNode", apply: bool, t0q=None, lats=None) -> int:
        """Consume node 0's commit stream; apply; record wall-clock
        propose→apply latency by matching each group's applies (commit
        order) against its FIFO of propose timestamps (t0q)."""
        cnt = 0
        per_g: dict = {}
        while True:
            try:
                item = n0.commit_q.get_nowait()
            except Exception:
                break
            if item is None or not isinstance(item, tuple):
                continue
            from raftsql_tpu.runtime.db import _expand_commit_item
            for g, idx, cmd in _expand_commit_item(item, n0):
                if apply:
                    per_g.setdefault(g, []).append((cmd, idx))
                cnt += 1
        for g, items in per_g.items():
            fn = getattr(sms[g], "apply_batch", None)
            if fn is not None:
                errs = fn(items)
            else:
                errs = [sms[g].apply(cmd, idx) for cmd, idx in items]
            bad = [e for e in errs if e is not None]
            if bad:     # a commits/s number for failed applies is a lie
                raise RuntimeError(f"apply failed in group {g}: {bad[0]}")
        if t0q is not None and per_g:
            now = time.perf_counter()
            for g, items in per_g.items():
                q = t0q[g]
                for _ in range(min(len(items), len(q))):
                    lats.append(now - q.popleft())
        return cnt

    try:
        for n in nodes:
            n.start(threaded=False)
        # Elect every group: tick all nodes until each has a leader.
        import numpy as np
        for t in range(40 * cfg.election_ticks):
            for n in nodes:
                n.tick()
            hints = np.asarray(nodes[0].state.leader_hint)
            if t > cfg.election_ticks and (hints >= 0).all():
                break
        for n in nodes:
            if n.error is not None:   # e.g. a TCP bind lost to a racer
                raise RuntimeError(f"node {n.node_id} died during "
                                   f"warmup: {n.error}")
        hints = np.asarray(nodes[0].state.leader_hint)
        _log(f"  elected: {int((hints >= 0).sum())}/{groups} groups "
             f"after warmup")
        for n in nodes:     # drop compile/warmup skew from phase averages
            m = n.metrics
            m.ticks = 0
            m.t_stage_ms = m.t_device_ms = m.t_wal_ms = 0.0
            m.t_send_ms = m.t_publish_ms = 0.0
        best = 0.0
        repeat_rates: list = []
        # BENCH_DURABLE_ACTIVE=N: queue load at only the first N groups.
        # The durable tick's Python cost is proportional to ACTIVE groups
        # (vectorized masks give idle groups ~zero work, runtime/node.py
        # _wal_phase/_publish_phase); this knob separates "how many groups
        # can the host carry" (G) from "how many proposals/tick can it
        # push" (active * E) — at G=10k the saturated-everywhere point
        # measures Python object handling, not the runtime's scaling.
        active = int(os.environ.get("BENCH_DURABLE_ACTIVE", "0")) or groups
        active = min(active, groups)
        for _ in range(repeats):
            # Pre-queue ticks*E proposals per group at its leader.
            # kv keeps the original unique-key workload (comparable to
            # earlier recorded runs); sqlite uses one INSERT shape.
            if sm_kind == "sqlite":
                cmds = [mk_cmd.encode()] * (ticks * E)
            else:
                cmds = [f"SET k{i} v".encode() for i in range(ticks * E)]
            for g in range(active):
                h = int(hints[g])
                nodes[h if h >= 0 else 0].propose_many(g, cmds)
            drain(nodes[0], apply=False)        # discard warmup commits
            t0 = time.perf_counter()
            committed = 0
            for _ in range(ticks):
                for n in nodes:
                    n.tick()
                committed += drain(nodes[0], apply=True)
            dt = time.perf_counter() - t0
            rate = committed / dt
            m = nodes[0].metrics.snapshot()
            _log(f"  {committed} durable commits in {dt:.3f}s -> "
                 f"{rate:,.0f} commits/s ({dt / ticks * 1e3:.2f} ms/tick); "
                 f"phase_ms={m['phase_ms_per_tick']}")
            best = max(best, rate)
            repeat_rates.append(round(rate, 1))
        phase = nodes[0].metrics.snapshot()["phase_ms_per_tick"]

        # -- Latency phase (VERDICT r3 task 3): REAL wall-clock
        # propose→commit+apply per proposal, measured end to end on the
        # durable stack.  Load arrives at the service rate (E per group
        # per tick, the flow-control ceiling) instead of pre-queued, so
        # the number is pipeline latency, not backlog drain; the feeder
        # is the client and its cost is honestly on the clock.  The
        # active set is bounded so feeding doesn't dominate the tick.
        from collections import deque as _deque
        lat_active = min(active, int(os.environ.get(
            "BENCH_DURABLE_LAT_ACTIVE", "256")))
        lat_ticks = max(ticks, 16)
        t0q = [_deque() for _ in range(groups)]
        lats: list = []
        # Flush the throughput phase's in-flight pipeline tail BEFORE
        # arming timestamps: leftover commits would otherwise be matched
        # FIFO against the new t0s, shifting every sample early by the
        # pipeline depth.
        for _ in range(8):
            for n in nodes:
                n.tick()
            if drain(nodes[0], apply=True) == 0:
                break
        for t in range(lat_ticks):
            now = time.perf_counter()
            if sm_kind == "sqlite":
                cmds = [mk_cmd.encode()] * E
            else:
                cmds = [f"SET lat{t}_{i} v".encode() for i in range(E)]
            for g in range(lat_active):
                h = int(hints[g])
                nodes[h if h >= 0 else 0].propose_many(g, cmds)
                t0q[g].extend([now] * E)
            for n in nodes:
                n.tick()
            drain(nodes[0], apply=True, t0q=t0q, lats=lats)
        for _ in range(6):          # resolve the in-flight pipeline tail
            for n in nodes:
                n.tick()
            drain(nodes[0], apply=True, t0q=t0q, lats=lats)
        censored = sum(len(q) for q in t0q)
        lat_stats = None
        if lats:
            lats.sort()
            lat_stats = {
                "p50_ms": round(lats[int(0.5 * (len(lats) - 1))] * 1e3, 3),
                "p99_ms": round(lats[int(0.99 * (len(lats) - 1))] * 1e3, 3),
                "n": len(lats), "censored": censored,
                "active": lat_active, "load_per_tick": E}
            _log(f"  durable wall-clock latency ({lat_active} active, "
                 f"{E}/group/tick): p50={lat_stats['p50_ms']} ms "
                 f"p99={lat_stats['p99_ms']} ms over {len(lats)} acks, "
                 f"{censored} censored")
        return best, {"durable_phase_ms": phase,
                      "durable_tick_ms": round(sum(phase.values()), 3),
                      "durable_lat": lat_stats,
                      "repeat_rates": repeat_rates}
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def bench_http(groups: int, seconds: float, clients: int,
               fused: bool = False, device: bool = False,
               workers: int = 0):
    """BASELINE config 1: the real cluster driven over HTTP.

    The reference's observable unit of work is HTTP PUT -> 204 after
    commit + apply (/root/reference/httpapi.go:38-49); this is the one
    configuration the reference actually ships (Procfile), measured end
    to end with concurrent keep-alive HTTP clients.  Two deployments:
      - fused=False: three server/main.py OS processes, TCP raft
        transport (the reference's literal shape);
      - fused=True: ONE --fused process — all peers co-located, one
        device program per tick, same per-peer WAL durability (the
        TPU-native shape; no cross-process hops on the commit path).
    device=True (fused only): the server inherits the session's default
    JAX platform instead of the cpu pin — on a live chip this is the
    FULL stack (HTTP -> consensus device step on TPU -> WAL fsync ->
    SQLite apply -> 204) in one process.  Only valid while nothing else
    holds the single-client tunnel.
    Reports req/s and true per-request wall-clock latency percentiles.
    """
    import http.client
    import shutil
    import socket
    import subprocess as sp
    import tempfile
    import threading

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    n_procs = 1 if fused else 3
    raft_ports = [free_port() for _ in range(3)]
    api_ports = [free_port() for _ in range(n_procs)]
    cluster = ",".join(f"http://127.0.0.1:{p}" for p in raft_ports)
    tmp = tempfile.mkdtemp(prefix="bench-http-")
    env = dict(os.environ)
    if device and fused:
        env.pop("JAX_PLATFORMS", None)     # the chip, via the tunnel
    else:
        env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(os.path.join(tmp, "servers.log"), "w")
    procs = []
    try:
        tick = os.environ.get("BENCH_HTTP_TICK", "0.005")
        engine = os.environ.get("BENCH_HTTP_ENGINE", "aio")
        if fused:
            procs.append(sp.Popen(
                [sys.executable, "-m", "raftsql_tpu.server.main",
                 "--fused", "--port", str(api_ports[0]),
                 "--groups", str(groups), "--tick", tick,
                 "--http-engine", engine]
                + (["--workers", str(workers)] if workers else []),
                cwd=tmp, env=env, stdout=logf, stderr=logf))
        else:
            for i in range(3):
                procs.append(sp.Popen(
                    [sys.executable, "-m", "raftsql_tpu.server.main",
                     "--cluster", cluster, "--id", str(i + 1),
                     "--port", str(api_ports[i]),
                     "--groups", str(groups), "--tick", tick,
                     "--http-engine", engine],
                    cwd=tmp, env=env, stdout=logf, stderr=logf))
        # Readiness: PUT blocks until commit+apply, so the first 204
        # proves election + full pipeline.  Schema per group.
        # Device servers pay tunnel init + one compile before the first
        # 204 can happen; triple the bring-up budget for that rung.
        deadline = time.monotonic() + (360 if device else 120)
        for g in range(groups):
            while True:
                if time.monotonic() > deadline:
                    with open(os.path.join(tmp, "servers.log")) as f:
                        tail = f.read()[-800:]
                    raise RuntimeError(
                        "cluster not ready in 120s; servers.log tail: "
                        + tail)
                try:
                    c = http.client.HTTPConnection("127.0.0.1",
                                                   api_ports[0], timeout=10)
                    try:
                        c.request("PUT", "/",
                                  body=b"CREATE TABLE t (v text)",
                                  headers={"X-Raft-Group": str(g)})
                        # 204 = created; 400 "already exists" = an
                        # earlier attempt (whose ack we missed to a
                        # client timeout) committed + applied — either
                        # way the full pipeline answered, i.e. the
                        # cluster is serving.
                        if c.getresponse().status in (204, 400):
                            break
                    finally:
                        c.close()
                except OSError:
                    pass
                time.sleep(0.5)
        _log(f"  cluster of {n_procs} ready ({groups} groups) on api "
             f"ports {api_ports}")

        # Load plane: the C++ epoll generator when the toolchain is up
        # (BENCH_HTTP_LOADGEN=python forces the thread-per-client
        # fallback).  The Python clients cost ~120-250us of interpreter
        # time per request ON THE SERVER'S CORES — at 192 clients they
        # are half the measured ceiling (3.9k vs 7.1k req/s, fused).
        loadgen = None
        if os.environ.get("BENCH_HTTP_LOADGEN", "native") == "native":
            from raftsql_tpu.native.build import build_http_load
            loadgen = build_http_load()
        if loadgen is not None:
            out = sp.run(
                [loadgen, str(seconds), str(clients), str(groups)]
                + [str(p) for p in api_ports],
                capture_output=True, text=True, timeout=seconds + 60)
            if out.returncode != 0:
                raise RuntimeError(f"http_load rc={out.returncode}: "
                                   f"{out.stderr[-400:]}")
            j = json.loads(out.stdout.strip())
            if not j["n"]:
                raise RuntimeError(
                    f"no successful PUTs ({j['errors']} errors)")
            got = None
            for p in api_ports:
                c = http.client.HTTPConnection("127.0.0.1", p, timeout=10)
                c.request("GET", "/", body=b"SELECT count(*) FROM t")
                r = c.getresponse()
                got = r.read().decode()
                assert r.status == 200, (r.status, got)
                c.close()
            rate = j["n"] / j["secs"]
            stats = {"p50_ms": j["p50_ms"], "p99_ms": j["p99_ms"],
                     "n": j["n"], "errors": j["errors"],
                     "clients": clients, "groups": groups,
                     "replica_rows": got.strip(),
                     "deploy": "fused-1proc" if fused else "3proc",
                     "loadgen": "native",
                     "req_per_s": round(rate, 1)}
            _log(f"  {j['n']} HTTP PUTs (native loadgen) in "
                 f"{j['secs']:.1f}s -> {rate:,.0f} req/s; "
                 f"p50={j['p50_ms']} ms p99={j['p99_ms']} ms, "
                 f"{j['errors']} errors")
            return rate, {"http_lat": stats}

        stop_at = time.monotonic() + seconds
        lats: list = []
        errs = [0]
        mu = threading.Lock()

        def client(ci: int) -> None:
            port = api_ports[ci % n_procs]
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
            my_lats = []
            my_errs = 0
            k = 0
            while time.monotonic() < stop_at:
                g = (ci + k) % groups
                k += 1
                t0 = time.perf_counter()
                try:
                    conn.request(
                        "PUT", "/",
                        body=f"INSERT INTO t (v) VALUES ('c{ci}_{k}')"
                        .encode(),
                        headers={"X-Raft-Group": str(g)})
                    ok = conn.getresponse()
                    ok.read()
                    if ok.status != 204:
                        my_errs += 1
                        continue
                except OSError:
                    my_errs += 1
                    conn = http.client.HTTPConnection("127.0.0.1", port,
                                                      timeout=30)
                    continue
                my_lats.append(time.perf_counter() - t0)
            with mu:
                lats.extend(my_lats)
                errs[0] += my_errs
            conn.close()

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        # Read-side spot check: every replica serves the (stale-ok) read.
        got = None
        for p in api_ports:
            c = http.client.HTTPConnection("127.0.0.1", p, timeout=10)
            c.request("GET", "/", body=b"SELECT count(*) FROM t")
            r = c.getresponse()
            got = r.read().decode()
            assert r.status == 200, (r.status, got)
            c.close()
        if not lats:
            raise RuntimeError(f"no successful PUTs ({errs[0]} errors)")
        lats.sort()

        def pct(p):
            return round(lats[int(p * (len(lats) - 1))] * 1e3, 3)

        rate = len(lats) / dt
        stats = {"p50_ms": pct(0.5), "p99_ms": pct(0.99),
                 "n": len(lats), "errors": errs[0], "clients": clients,
                 "groups": groups, "replica_rows": got.strip(),
                 "deploy": "fused-1proc" if fused else "3proc",
                 "req_per_s": round(rate, 1)}
        _log(f"  {len(lats)} HTTP PUTs in {dt:.1f}s -> {rate:,.0f} req/s; "
             f"p50={stats['p50_ms']} ms p99={stats['p99_ms']} ms, "
             f"{errs[0]} errors")
        return rate, {"http_lat": stats}
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:
                p.kill()
        logf.close()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_georeads(seconds: float = 5.0, rtt_ms: float = 60.0,
                   sites: int = 4, threads_per_site: int = 2,
                   think_ms: float = 50.0):
    """BENCH_CONFIG=georeads: the read-replica tier scaling ladder.

    The geo model: `sites` client sites, each `rtt_ms` away from the
    write tier.  One fused engine publishes the shm delta stream
    (--replica-listen); up to 4 `python -m raftsql_tpu.replica`
    processes subscribe.  A site with a LOCAL replica reads session
    mode at zero injected latency; a site without one pays the
    upstream RTT per read (injected client-side — the engine is on
    this box).  Rungs N=1/2/4 replicas measure aggregate session
    reads/s across all sites with a fixed watermark workload: every
    rung converts far sites into near ones, so the ladder is the
    read-scaling story the tier exists for.  Clients are CLOSED-LOOP
    with a per-request think time — the geo win is latency avoided
    per read, and an open-loop hammer on a small shared box would
    measure CPU contention instead of it.  A replica REFUSAL (421)
    falls back to the write tier (paying the RTT) and is counted —
    fail-closed never subtracts from correctness, only from the rate.
    Headline = reads/s at the 4-replica rung.
    """
    import http.client
    import shutil
    import socket
    import subprocess as sp
    import tempfile
    import threading

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    groups = int(os.environ.get("BENCH_GROUPS", "2"))
    max_replicas = 4
    api_port = free_port()
    stream_port = free_port()
    http_ports = [free_port() for _ in range(max_replicas)]
    tmp = tempfile.mkdtemp(prefix="bench-georeads-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    repo = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(os.path.join(tmp, "servers.log"), "w")
    procs = []
    rtt_s = rtt_ms / 1e3
    try:
        procs.append(sp.Popen(
            [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
             "--port", str(api_port), "--groups", str(groups),
             "--tick", "0.02", "--lease-ticks", "40",
             "--replica-listen", str(stream_port)],
            cwd=tmp, env=env, stdout=logf, stderr=logf))
        deadline = time.monotonic() + 120
        for g in range(groups):
            while True:
                if time.monotonic() > deadline:
                    with open(os.path.join(tmp, "servers.log")) as f:
                        tail = f.read()[-800:]
                    raise RuntimeError("engine not ready in 120s: " + tail)
                try:
                    c = http.client.HTTPConnection(
                        "127.0.0.1", api_port, timeout=10)
                    try:
                        c.request("PUT", "/",
                                  body=b"CREATE TABLE t (v text)",
                                  headers={"X-Raft-Group": str(g)})
                        if c.getresponse().status in (204, 400):
                            break
                    finally:
                        c.close()
                except OSError:
                    pass
                time.sleep(0.5)
        # The dataset + the session watermark each reader will carry.
        wm = ["0"] * groups
        for n in range(groups * 25):
            g = n % groups
            c = http.client.HTTPConnection("127.0.0.1", api_port,
                                           timeout=10)
            c.request("PUT", "/", body=f"INSERT INTO t VALUES ('v{n}')"
                      .encode(), headers={"X-Raft-Group": str(g)})
            r = c.getresponse()
            assert r.status == 204, (r.status, r.read())
            wm[g] = r.headers.get("X-Raft-Session", wm[g])
            c.close()
        # All four replicas boot once; each rung reads from a subset.
        for i in range(max_replicas):
            procs.append(sp.Popen(
                [sys.executable, "-m", "raftsql_tpu.replica",
                 "--upstream", f"127.0.0.1:{stream_port}",
                 "--port", str(http_ports[i]),
                 "--advertise", f"127.0.0.1:{http_ports[i]}"],
                cwd=tmp, env=env, stdout=logf, stderr=logf))
        deadline = time.monotonic() + 120
        for i in range(max_replicas):
            while True:
                if time.monotonic() > deadline:
                    with open(os.path.join(tmp, "servers.log")) as f:
                        tail = f.read()[-800:]
                    raise RuntimeError(
                        f"replica {i} not serving in 120s: " + tail)
                try:
                    c = http.client.HTTPConnection(
                        "127.0.0.1", http_ports[i], timeout=5)
                    try:
                        c.request("GET", "/",
                                  body=b"SELECT count(*) FROM t",
                                  headers={"X-Consistency": "session",
                                           "X-Raft-Session": wm[0],
                                           "X-Raft-Group": "0"})
                        if c.getresponse().status == 200:
                            break
                    finally:
                        c.close()
                except OSError:
                    pass
                time.sleep(0.3)
        _log(f"  engine + {max_replicas} replicas serving "
             f"({groups} groups, rtt={rtt_ms}ms)")

        think_s = think_ms / 1e3

        def site_reader(site: int, idx: int, n_replicas: int,
                        stop: list, out: list) -> None:
            near = site < n_replicas
            port = http_ports[site] if near else api_port
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=10)
            near_reads = far_reads = fallbacks = refusals = 0
            it = 0
            try:
                while not stop:
                    g = it % groups
                    it += 1
                    if not near:
                        time.sleep(rtt_s)   # the injected upstream hop
                    try:
                        conn.request(
                            "GET", "/", body=b"SELECT count(*) FROM t",
                            headers={"X-Consistency": "session",
                                     "X-Raft-Session": wm[g],
                                     "X-Raft-Group": str(g)})
                        st = conn.getresponse()
                        st.read()
                        status = st.status
                    except OSError:
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=10)
                        continue
                    if status == 200:
                        if near:
                            near_reads += 1
                        else:
                            far_reads += 1
                    elif near and status == 421:
                        # Fail-closed replica: pay the trip upstream.
                        refusals += 1
                        time.sleep(rtt_s)
                        ec = http.client.HTTPConnection(
                            "127.0.0.1", api_port, timeout=10)
                        try:
                            ec.request(
                                "GET", "/",
                                body=b"SELECT count(*) FROM t",
                                headers={"X-Consistency": "session",
                                         "X-Raft-Session": wm[g],
                                         "X-Raft-Group": str(g)})
                            er = ec.getresponse()
                            er.read()
                            if er.status == 200:
                                fallbacks += 1
                        finally:
                            ec.close()
                    time.sleep(think_s)     # closed-loop client pacing
            finally:
                conn.close()
            out[idx] = (near_reads, far_reads, fallbacks, refusals)

        ladder: dict = {}
        detail: dict = {}
        best = 0.0
        for n_replicas in (1, 2, 4):
            stop: list = []
            out: list = [None] * (sites * threads_per_site)
            ts = []
            for site in range(sites):
                for k in range(threads_per_site):
                    idx = site * threads_per_site + k
                    ts.append(threading.Thread(
                        target=site_reader,
                        args=(site, idx, n_replicas, stop, out),
                        daemon=True))
            t0 = time.monotonic()
            for t in ts:
                t.start()
            time.sleep(seconds)
            stop.append(True)
            for t in ts:
                t.join(timeout=30)
            dt = time.monotonic() - t0
            rows = [r for r in out if r is not None]
            near_reads = sum(r[0] for r in rows)
            far_reads = sum(r[1] for r in rows)
            fallbacks = sum(r[2] for r in rows)
            refusals = sum(r[3] for r in rows)
            rate = (near_reads + far_reads + fallbacks) / dt
            best = max(best, rate)
            ladder[str(n_replicas)] = round(rate, 1)
            detail[str(n_replicas)] = {
                "reads_per_s": round(rate, 1),
                "replica_hits": near_reads, "upstream_reads": far_reads,
                "engine_fallbacks": fallbacks, "refusals": refusals,
                "near_sites": min(n_replicas, sites)}
            _log(f"  georeads rung N={n_replicas}: "
                 f"{rate:,.0f} reads/s ({near_reads} replica, "
                 f"{far_reads} upstream, {fallbacks} fallbacks, "
                 f"{refusals} refusals)")
        extras = {"georeads_ladder": ladder, "georeads": detail,
                  "injected_rtt_ms": rtt_ms, "think_ms": think_ms,
                  "sites": sites,
                  "threads_per_site": threads_per_site,
                  "cpu_count": os.cpu_count()}
        return float(ladder["4"]), extras
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except Exception:                       # noqa: BLE001
                p.kill()
        logf.close()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_durable_fused(groups: int, peers: int, ticks: int, repeats: int,
                        runtime: str = "fused"):
    """The durable path on the FUSED runtime (runtime/fused.py): all P
    peers advance in ONE device program per tick, per-peer WAL fsync is
    the inter-dispatch barrier (save-before-send), KV apply off peer 0's
    commit stream.

    This is the TPU-shaped durable deployment: the per-node runtime pays
    one dispatch per peer per tick, which through a remote tunnel is
    dispatch-bound (~70 ms/exec); the fused runtime pays one dispatch
    per CLUSTER per tick, so durable throughput scales with G x E per
    dispatch instead of drowning in per-peer overhead.

    runtime="mesh" runs the SAME bench on the MESH runtime
    (runtime/mesh.py MeshClusterNode): the device step shard_map'd with
    G sharded over the widest groups-only mesh the visible devices
    allow, per-shard WAL dirs and per-shard publish workers — the
    multi-chip G-scale durable rung (groups is rounded down to a
    multiple of the shard count).
    """
    import shutil
    import tempfile
    from collections import deque as _deque

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.kv_sm import KVStateMachine
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import _expand_commit_item
    from raftsql_tpu.runtime.fused import FusedClusterNode

    E = int(os.environ.get("BENCH_E", "8"))
    mesh_cfg = None
    if runtime == "mesh":
        from raftsql_tpu.runtime.mesh import MeshConfig
        import jax as _jax
        gg = min(len(_jax.devices()), groups)
        groups -= groups % gg           # divisibility for the mesh
        mesh_cfg = MeshConfig(group_shards=gg)
    cfg = RaftConfig(num_groups=groups, num_peers=peers,
                     log_window=max(64, 4 * E),
                     max_entries_per_msg=E, tick_interval_s=0.0)
    tmp = tempfile.mkdtemp(prefix=f"bench-{runtime}-")
    # BENCH_SM=sqlite: the reference-parity apply engine (one SQLite
    # database per group, group-committed transactions) — the FULL
    # product stack on the fused runtime.  Default: the C++ KV plane
    # (models/kv_native.py) applied straight from the native payload
    # log — the measured fastest durable deployment (525k vs 329k
    # commits/s at G=1000/E=32 on one CPU core).  BENCH_DURABLE_APPLY=
    # python forces the Python-resident KV consumer; =native makes a
    # missing toolchain an error instead of a fallback.
    apply_req = os.environ.get("BENCH_DURABLE_APPLY", "")
    if apply_req == "native" and os.environ.get("BENCH_SM") == "sqlite":
        raise RuntimeError(
            "BENCH_DURABLE_APPLY=native conflicts with BENCH_SM=sqlite "
            "(the native plane is the KV apply engine)")
    # The mesh runtime publishes from one worker PER GROUP SHARD; the
    # in-process C KV apply is a single-consumer design, so the mesh
    # rung defaults to the queue-drain apply path (opt back in with
    # BENCH_DURABLE_APPLY=native once the C plane is audited for
    # concurrent disjoint-group applies).
    native_apply = (apply_req != "python"
                    and os.environ.get("BENCH_SM") != "sqlite"
                    and (runtime != "mesh" or apply_req == "native"))
    if native_apply:
        os.environ["RAFTSQL_FUSED_NATIVE_PLOG"] = "1"
    sm_kind = ("sqlite" if os.environ.get("BENCH_SM") == "sqlite"
               else ("kv-native" if native_apply else "kv"))
    if sm_kind == "sqlite":
        sms = [SQLiteStateMachine(os.path.join(tmp, f"sm-{g}.db"))
               for g in range(groups)]
        for g, sm in enumerate(sms):
            err = sm.apply("CREATE TABLE t (v text)", 0)
            assert err is None, err
        mk_cmd = b"INSERT INTO t (v) VALUES ('x')"
    else:
        sms = [KVStateMachine() for _ in range(groups)]
        mk_cmd = None                      # kv: unique keys per batch

    from raftsql_tpu.runtime.db import (iter_plain_batches,
                                        iter_plain_entries)
    from raftsql_tpu.runtime.node import RAW_MANY, RAW_PLAIN

    def drain(node, apply: bool, t0q=None, lats=None) -> int:
        cnt = 0
        per_g: dict = {}
        q = node.commit_q(0)
        while True:
            try:
                item = q.get_nowait()
            except Exception:
                break
            if item is None or not isinstance(item, tuple):
                continue
            if item[0] is RAW_PLAIN or item[0] is RAW_MANY:
                # The fused publish batches per group (RAW_PLAIN) or per
                # tick (RAW_MANY): decode in place (runtime/db.py owns
                # the plain-payload contract) instead of expanding to
                # per-entry tuples first.
                for g, base, datas in iter_plain_batches(item):
                    if apply:
                        lst = per_g.setdefault(g, [])
                        for idx, cmd in iter_plain_entries(base, datas):
                            lst.append((cmd, idx))
                            cnt += 1
                    else:
                        cnt += sum(1 for d in datas if d)
                continue
            for g, idx, cmd in _expand_commit_item(item):
                if apply:
                    per_g.setdefault(g, []).append((cmd, idx))
                cnt += 1
        for g, items in per_g.items():
            for err in sms[g].apply_batch(items):
                if err is not None:
                    raise RuntimeError(f"apply failed g{g}: {err}")
        if t0q is not None and per_g:
            now = time.perf_counter()
            for g, items in per_g.items():
                fifo = t0q[g]
                for _ in range(min(len(items), len(fifo))):
                    lats.append(now - fifo.popleft())
        return cnt

    if mesh_cfg is not None:
        from raftsql_tpu.runtime.mesh import MeshClusterNode
        mesh = mesh_cfg.build()
        _log(f"  mesh durable: 1x{mesh_cfg.group_shards} devices, "
             f"{groups} groups ({groups // mesh_cfg.group_shards} per "
             f"shard), per-shard WAL dirs + publish workers")
        node = MeshClusterNode(cfg, tmp, mesh)
    else:
        # WAL group commit (PR 7): one shared log + one fsync per tick
        # for all P peers — the durable rung's default; 0 restores the
        # per-peer-file layout for A/Bs.
        node = FusedClusterNode(
            cfg, tmp,
            group_commit=os.environ.get(
                "BENCH_WAL_GROUP_COMMIT", "1") == "1")
    node.publish_peers = {0}       # the drain consumes peer 0's stream
    kv_native = None
    if native_apply and not hasattr(node.plogs[0], "handle"):
        if apply_req == "native":
            raise RuntimeError(
                "BENCH_DURABLE_APPLY=native needs the native plog")
        native_apply, sm_kind = False, "kv"     # toolchain-less host
    if native_apply:
        from raftsql_tpu.models.kv_native import NativeKV
        kv_native = NativeKV(groups, node._plog_lib)
        node.native_kv = kv_native
    try:
        for t in range(40 * cfg.election_ticks):
            node.tick()
            if t > cfg.election_ticks and (node._hints >= 0).all():
                break
        elected = int((node._hints >= 0).sum())
        _log(f"  fused: elected {elected}/{groups} groups "
             f"({node.metrics.ticks} warmup ticks)")
        m = node.metrics
        m.ticks = 0
        m.t_device_ms = m.t_wal_ms = m.t_publish_ms = 0.0
        active = int(os.environ.get("BENCH_DURABLE_ACTIVE", "0")) or groups
        active = min(active, groups)
        best = 0.0
        repeat_rates: list = []
        for _ in range(repeats):
            # Flush the previous repeat's in-flight tail (publish is
            # deferred one tick, commits lag ~3) so it cannot leak into
            # this repeat's timed window — then drop the idle flush
            # ticks from the phase averages (they would dilute
            # durable_tick_ms by ~20%).
            for _ in range(6):
                node.tick()
                drain(node, apply=False)
            m = node.metrics
            m.ticks = 0
            m.t_device_ms = m.t_wal_ms = m.t_publish_ms = 0.0
            # Backlog for the whole run: each multi-step dispatch
            # drains S x E per group, so scale by steps or the later
            # dispatches run empty and dilute the rate.
            per_g = ticks * E * node._steps
            cmds = ([mk_cmd] * per_g if mk_cmd is not None else
                    [f"SET k{i} v".encode() for i in range(per_g)])
            for g in range(active):
                node.propose_many(g, cmds)
            drain(node, apply=False)
            # Drain+apply rides the runtime's overlap hook: through a
            # remote-device tunnel the dispatch+compute window is idle
            # host time, so the apply plane runs there for free (on a
            # local backend it's equivalent to draining after tick()).
            applied = 0

            def hook():
                nonlocal applied
                applied += drain(node, apply=True)

            base_applied = kv_native.total_applied if kv_native else 0
            node.overlap_hook = hook
            t0 = time.perf_counter()
            for _ in range(ticks):
                node.tick()
            node.overlap_hook = None
            # Retire the async publisher's backlog: the rate counts a
            # commit only once it reached the apply plane.
            node.publish_flush()
            committed = applied + drain(node, apply=True)
            if kv_native is not None:
                # The C plane applied inside _publish; the queue drain
                # above only flushed stragglers (normally zero).
                committed += kv_native.total_applied - base_applied
            dt = time.perf_counter() - t0
            rate = committed / dt
            _log(f"  {committed} fused durable commits in {dt:.3f}s -> "
                 f"{rate:,.0f} commits/s ({dt / ticks * 1e3:.2f} ms/tick)")
            best = max(best, rate)
            repeat_rates.append(round(rate, 1))
        snap = node.metrics.snapshot()["phase_ms_per_tick"]
        phase = {k: snap[k] for k in ("device", "wal", "publish")}

        # Wall-clock propose→apply latency at the service rate.
        lat_active = min(active, int(os.environ.get(
            "BENCH_DURABLE_LAT_ACTIVE", "256")))
        lat_ticks = max(ticks, 16)
        t0q = [_deque() for _ in range(groups)]
        lats: list = []
        for _ in range(8):
            node.tick()
            if drain(node, apply=True) == 0 and kv_native is None:
                break
            # native mode: run the full 8 flush ticks (the queue is
            # always empty; prev_ap below absorbs the pipeline tail).

        if kv_native is not None:
            # The C plane applies inside _publish: ack by watching each
            # active group's applied index advance.
            prev_ap = [kv_native.applied_index(g)
                       for g in range(lat_active)]

        def settle_native():
            now2 = time.perf_counter()
            for g in range(lat_active):
                a = kv_native.applied_index(g)
                fifo = t0q[g]
                for _ in range(min(a - prev_ap[g], len(fifo))):
                    lats.append(now2 - fifo.popleft())
                prev_ap[g] = a

        for t in range(lat_ticks):
            now = time.perf_counter()
            cmds = ([mk_cmd] * E if mk_cmd is not None else
                    [f"SET lat{t}_{i} v".encode() for i in range(E)])
            for g in range(lat_active):
                node.propose_many(g, cmds)
                t0q[g].extend([now] * E)
            node.tick()
            if kv_native is not None:
                settle_native()
            else:
                drain(node, apply=True, t0q=t0q, lats=lats)
        for _ in range(6):
            node.tick()
            node.publish_flush()    # acks land via the async publisher
            if kv_native is not None:
                settle_native()
            else:
                drain(node, apply=True, t0q=t0q, lats=lats)
        censored = sum(len(q) for q in t0q)
        lat_stats = None
        if lats:
            lats.sort()
            lat_stats = {
                "p50_ms": round(lats[int(0.5 * (len(lats) - 1))] * 1e3, 3),
                "p99_ms": round(lats[int(0.99 * (len(lats) - 1))] * 1e3, 3),
                "n": len(lats), "censored": censored,
                "active": lat_active, "load_per_tick": E}
            _log(f"  fused durable latency: p50={lat_stats['p50_ms']} ms "
                 f"p99={lat_stats['p99_ms']} ms over {len(lats)} acks, "
                 f"{censored} censored")
        # On parallel hosts publish runs on its own worker, overlapped
        # with the next tick's device+wal phases — summing it into the
        # tick would double-count wall time the tick thread never spent.
        overlapped = node._host_parallel
        tick_ms = sum(v for k, v in phase.items()
                      if not (overlapped and k == "publish"))
        out = {"durable_mode": runtime, "durable_sm": sm_kind,
               "durable_steps": node._steps,
               "durable_phase_ms": phase,
               "durable_phase_overlap": overlapped,
               "durable_tick_ms": round(tick_ms, 3),
               "durable_lat": lat_stats,
               "repeat_rates": repeat_rates,
               # Serving-stack levers (PR 7): double-buffered dispatch
               # engagement + the group-commit batch-size histogram
               # (peers coalesced per fsync -> count).
               "overlap_ticks": node.metrics.overlap_ticks}
        # Tick-phase profile (PR 8, obs/prof.py, default on —
        # RAFTSQL_PROF=0 for the A/B): per-phase shares of tick time
        # (fsync vs dispatch vs publish) + the p50/p95/p99 window, so
        # the BENCH_*.json trajectory shows WHY a rung moved, not just
        # that it did.
        prof = getattr(node, "prof", None)
        if prof is not None:
            out["phase_profile"] = {**prof.shares(),
                                    "phases": prof.snapshot()}
        gcw = getattr(node, "_gcwal", None)
        if gcw is not None:
            out["wal_group_commits"] = gcw.group_commits
            out["wal_gc_batch_hist"] = {
                str(k): v for k, v in sorted(gcw.batch_hist.items())}
        if mesh_cfg is not None:
            out["mesh_group_shards"] = mesh_cfg.group_shards
            out["mesh_groups"] = groups
        return best, out
    finally:
        node.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def bench_rules_race(groups: int, peers: int, ticks: int, repeats: int
                     ) -> dict:
    """Race the three commit-advance kernels, small-P AND large-P.

    VERDICT r2 task 6 / r4 task 7: `point` (etcd maybeCommit shortcut),
    `windowed` (masked ring scan) and `pallas` (hand-written kernel),
    each its own jit (commit_rule is static config).  The pallas
    kernel's claimed regime is large peer counts (its O(P^2) comparison
    network vs XLA's sort, ops/pallas_quorum.py) — so the race runs the
    requested P and a P=15 shape; the large-P winner is the evidence
    for (or against) keeping the kernel as the large-P default.
    """
    out: dict = {}
    shapes = [(f"P{peers}", groups, peers)]
    big_p = int(os.environ.get("BENCH_RULES_BIG_P", "15"))
    if big_p > peers:
        # Same total work scale: G x P stays comparable.
        shapes.append((f"P{big_p}", max(groups * peers // big_p, 64),
                       big_p))
    # BENCH_RULES_SET splits the race across child processes: the
    # parent runs point+windowed in one child and pallas in another so
    # a pallas compile hang (observed: P=15 on the device) costs only
    # its own child's timeout, never the XLA rules' JSON.
    rules_set = tuple(
        r for r in os.environ.get(
            "BENCH_RULES_SET", "point,windowed,pallas").split(",") if r)
    for label, g, p in shapes:
        row = {}
        for rule in rules_set:
            _log(f"== commit_rule={rule} (G={g}, P={p}) ==")
            try:
                row[rule] = round(
                    bench_throughput(g, p, ticks, repeats,
                                     commit_rule=rule), 1)
            except Exception as e:                  # noqa: BLE001
                _log(f"  commit_rule={rule} FAILED: "
                     f"{type(e).__name__}: {e}")
                row[rule] = f"fault: {type(e).__name__}"
        out[label] = row
        _log(f"rules race {label}: {row}")
    return out


def run_config(config: str, cpu: bool):
    """Dispatch one BENCH_CONFIG; defaults scale down on cpu so the
    fallback path still finishes inside the driver's time budget.

    Returns (headline_value, extras_dict) — extras are merged into the
    child's JSON line for the driver/judge to record.
    """
    # cpu default 2048: measured 6.7M commits/s vs 5.4M at 4096 (E=32) —
    # the fallback headline should be the best CPU point, not a scaled
    # copy of the TPU shape.
    groups = int(os.environ.get("BENCH_GROUPS", 2048 if cpu else 100_000))
    peers = int(os.environ.get("BENCH_PEERS", 3))
    ticks = int(os.environ.get("BENCH_TICKS", 120 if cpu else 400))
    repeats = int(os.environ.get("BENCH_REPEATS", 2 if cpu else 3))
    egroups = int(os.environ.get("BENCH_GROUPS", 2048 if cpu else 10_000))

    if config == "all":
        results = {}
        _log("== config 2: 1k x 3 quorum replication ==")
        results["quorum_1k_x3"] = bench_throughput(1000, 3, ticks, repeats)
        _log("== config 3: elections ==")
        results["elections"] = bench_elections(egroups, 5, repeats)
        _log("== config 4: commit scan ==")
        results["commit_scan"] = bench_commit_scan(
            20_000 if cpu else 100_000, repeats)
        _log("== config 5: mesh-sharded cluster ==")
        results["multichip"] = bench_multichip(ticks, repeats)
        _log("== headline: G x P saturated throughput ==")
        results["headline"] = bench_throughput(groups, peers, ticks, repeats)
        for k, v in results.items():
            _log(f"{k}: {v:,.0f}/s")
        return results["headline"], {}
    if config == "quorum":
        return bench_throughput(1000, 3, ticks, repeats), {}
    if config == "elections":
        return bench_elections(egroups, 5, repeats), {}
    if config == "commit_scan":
        return bench_commit_scan(groups, repeats), {}
    if config == "multichip":
        # Multi-chip G-scale ladder (MULTICHIP-style JSON): sweep total
        # group counts over the mesh, smallest first, and headline the
        # best rung — how far the pod takes G past the one-chip shape.
        import jax as _jax
        gg = max(1, len(_jax.devices()) // (
            2 if len(_jax.devices()) % 2 == 0
            and len(_jax.devices()) > 1 else 1))
        default = ",".join(str(g * gg) for g in (1024, 8192, 32768))
        rungs = [int(x) for x in os.environ.get(
            "BENCH_MESH_LADDER", default).split(",") if x]
        ladder: dict = {}
        best = 0.0
        for g in rungs:
            _log(f"== multichip rung G={g} ==")
            try:
                r = bench_multichip(ticks, repeats, groups=g)
                ladder[str(g)] = round(r, 1)
                best = max(best, r)
            except Exception as e:                  # noqa: BLE001
                _log(f"  multichip G={g} FAILED: "
                     f"{type(e).__name__}: {e}")
                ladder[str(g)] = f"fault: {type(e).__name__}"
        extras = {"mesh_ladder": ladder,
                  "mesh_devices": len(_jax.devices())}
        # BENCH_POD_PROCS=N: the multi-host pod rung — N real dry-run
        # processes over the TCP collective, attributing the cross-host
        # hop cost per tick (bench_pod_rung).
        pod_procs = int(os.environ.get("BENCH_POD_PROCS", "0"))
        if pod_procs > 0:
            _log(f"== pod rung: {pod_procs} host processes ==")
            extras["pod"] = bench_pod_rung(pod_procs, ticks)
        return best, extras
    if config == "rules":
        out = bench_rules_race(groups, peers, ticks, repeats)
        vals = [v for row in out.values() for v in row.values()
                if isinstance(v, float)]
        return (max(vals) if vals else 0.0), {"rules": out}
    if config == "latency":
        sweep = bench_latency_sweep(groups, peers, repeats)
        return (_light_row(sweep).get("p50_ms") or 0.0, {"lat": sweep})
    if config == "reads":
        return bench_reads(
            peers, seconds=float(os.environ.get("BENCH_READ_SECONDS",
                                                "2")))
    if config == "georeads":
        return bench_georeads(
            seconds=float(os.environ.get("BENCH_GEO_SECONDS", "5")),
            rtt_ms=float(os.environ.get("BENCH_GEO_RTT_MS", "60")),
            think_ms=float(os.environ.get("BENCH_GEO_THINK_MS", "50")))
    if config == "http":
        # Two rungs: 16 clients (the reference's concurrency scale,
        # raftsql_test.go:79-90 — a LATENCY point) and a high-concurrency
        # rung (throughput point: concurrent proposals amortize into one
        # tick batch; on a small host the bench clients share the
        # server's cores, so this is a lower bound).  Headline = the
        # better req/s; both rungs + cpu count ride the extras JSON.
        g = int(os.environ.get("BENCH_GROUPS", "8"))
        secs = float(os.environ.get("BENCH_HTTP_SECONDS", "10"))
        c16 = int(os.environ.get("BENCH_HTTP_CLIENTS", "16"))
        chi = int(os.environ.get("BENCH_HTTP_CLIENTS_HI", "192"))
        extras = {"cpu_count": os.cpu_count()}
        best = 0.0
        if c16 > 0:       # 0 skips a rung (engine/deployment A/Bs)
            rate16, ex16 = bench_http(g, secs, c16)
            extras["http_lat"] = ex16["http_lat"]
            best = rate16
        # Further rungs, best-effort: high concurrency on the 3-process
        # cluster, then the --fused single-process deployment (the
        # TPU-native shape) at both client counts.
        rungs = [("http_lat_hi", chi, False, False, 0),
                 ("http_lat_fused", c16, True, False, 0),
                 ("http_lat_fused_hi", chi, True, False, 0)]
        # Multi-worker serving ladder (PR 7, runtime/ring.py): the
        # fused engine behind 1/2/4/8 SO_REUSEPORT HTTP worker
        # processes at high concurrency — the req/s-vs-workers scaling
        # story.  BENCH_HTTP_WORKERS_LADDER= (empty) skips it.
        for w in (int(x) for x in os.environ.get(
                "BENCH_HTTP_WORKERS_LADDER", "1,2,4,8").split(",")
                if x):
            rungs.append((f"http_workers_{w}", chi, True, False, w))
        if os.environ.get("BENCH_HTTP_DEVICE") == "1":
            # config-1 ON THE DEVICE: the fused server inherits the
            # session platform (the chip via the tunnel), the full
            # HTTP -> device step -> WAL -> SQLite -> 204 stack.
            rungs.append(("http_lat_fused_tpu",
                          int(os.environ.get("BENCH_HTTP_CLIENTS_TPU",
                                             "192")), True, True, 0))
        ladder: dict = {}
        for key, clients, fused, device, workers in rungs:
            if clients <= 0:
                continue
            try:
                r, ex = bench_http(g, secs, clients, fused=fused,
                                   device=device, workers=workers)
                best = max(best, r)
                extras[key] = ex["http_lat"]
                if workers:
                    ladder[str(workers)] = round(r, 1)
            except Exception as e:                  # noqa: BLE001
                _log(f"  http rung {key} FAILED: {e}")
                extras[key] = {"error": str(e)}
                if workers:
                    ladder[str(workers)] = f"fault: {e}"
        if ladder:
            extras["http_workers_ladder"] = ladder
        return best, extras
    if config == "durable":
        # sqlite keeps one DB file (3 fds with -wal/-shm) per group: stay
        # well under the default open-files rlimit.
        default_g = (256 if os.environ.get("BENCH_SM") == "sqlite"
                     else 1000 if cpu else 10_000)
        dg = int(os.environ.get("BENCH_GROUPS", default_g))
        dticks = int(os.environ.get("BENCH_TICKS", 24))
        # Mode: "node" = 3 RaftNodes (per-peer dispatch, the distributed
        # runtime), "fused" = FusedClusterNode (one dispatch per cluster
        # tick — the only shape that isn't dispatch-bound through the
        # remote-TPU tunnel).  Default: fused on an accelerator, node on
        # cpu (keeps the historical CPU rung comparable).
        mode = os.environ.get("BENCH_DURABLE_MODE",
                              "node" if cpu else "fused")
        if mode == "mesh":
            # The multi-chip durable rung: MeshClusterNode over the
            # widest groups-only mesh, per-shard WAL + publish workers.
            return bench_durable_fused(dg, peers, dticks,
                                       min(repeats, 2), runtime="mesh")
        if mode == "fused":
            return bench_durable_fused(dg, peers, dticks,
                                       min(repeats, 2))
        return bench_durable(dg, peers, dticks, min(repeats, 2))
    # headline: saturated throughput + the latency/load sweep.
    stats: dict = {}
    value = bench_throughput(groups, peers, ticks, repeats, stats=stats)
    extras = {"p50_sat_ms": stats.get("p50_ms"),
              "tick_ms": stats.get("tick_ms"),
              "repeat_rates": stats.get("repeat_rates"),
              "repeat_spread": stats.get("repeat_spread")}
    if os.environ.get("BENCH_SKIP_SWEEP") != "1":
        sweep = bench_latency_sweep(groups, peers, max(1, repeats - 1))
        extras["lat"] = sweep
        extras["p50_light_ms"] = _light_row(sweep).get("p50_ms")
    return value, extras


def child_main() -> None:
    """One attempt: pin the requested platform, measure, print JSON."""
    import jax

    want = os.environ.get("BENCH_PLATFORM", "")
    if want:
        # sitecustomize imports jax before us, so JAX_PLATFORMS was already
        # captured from the env; update the live config.
        jax.config.update("jax_platforms", want)
    config = os.environ.get("BENCH_CONFIG", "headline")
    backend = jax.devices()[0].platform
    # The "axon" backend IS the remote TPU (a PJRT tunnel to one chip);
    # report it as tpu, keeping the raw backend name alongside.
    platform = "tpu" if backend == "axon" else backend
    _log(f"bench[{config}]: platform={platform} backend={backend} "
         f"devices={len(jax.devices())}")
    got = run_config(config, cpu=platform == "cpu")
    value, extras = got if isinstance(got, tuple) else (got, {})
    if config == "latency":
        # Latency headline: ms, lower is better; vs_baseline is the
        # ratio to the <2ms p50 north star (>=1 means target met).
        out = {
            "metric": "raft_propose_commit_p50_ms",
            "value": round(value, 3),
            "unit": "ms",
            "vs_baseline": round(2.0 / value, 4) if value > 0 else 0.0,
            "platform": platform,
            "backend": backend,
        }
    else:
        out = {
            "metric": "raft_commits_per_sec",
            "value": round(value, 1),
            "unit": "commits/s",
            "vs_baseline": round(value / NORTH_STAR_COMMITS_PER_SEC, 4),
            "platform": platform,
            "backend": backend,
        }
    out.update(extras)
    if platform == "tpu":
        # Regression tripwire (VERDICT r4 task 6): compare against the
        # ledger's newest same-shape/same-backend entry BEFORE appending
        # this run.  A >20% drop is flagged in the JSON and on stderr —
        # round 4's official numbers moved opposite to the claimed wins
        # and nothing noticed.
        shape = {"config": config,
                 "groups": os.environ.get("BENCH_GROUPS", ""),
                 "e": os.environ.get("BENCH_E", ""),
                 "sm": os.environ.get("BENCH_SM", "")}
        prev = _ledger_last_matching(shape)
        # Direction-aware: latency's value is p50 ms (lower = better);
        # everything else is commits/s (higher = better).
        lower_is_better = config == "latency"
        regressed = (prev and prev.get("value", 0) > 0
                     and (value > 1.25 * prev["value"] if lower_is_better
                          else value < 0.8 * prev["value"]))
        if regressed:
            delta = (value / prev["value"] - 1 if lower_is_better
                     else 1 - value / prev["value"])
            warn = {"prev_value": prev["value"],
                    "prev_ts": prev.get("ts"),
                    "prev_sha": prev.get("git_sha"),
                    "drop_pct": round(100 * delta, 1)}
            out["regression_warn"] = warn
            _log(f"bench: REGRESSION WARNING {config} shape {shape}: "
                 f"{value:,.1f} is {warn['drop_pct']}% below ledger "
                 f"{prev['value']:,.1f} ({prev.get('ts')} "
                 f"@ {prev.get('git_sha')})")
        # Durable evidence (VERDICT r3 task 1): a wedged tunnel at the
        # driver's capture time must never again erase a real TPU run.
        rec = dict(out)
        rec.update({
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "git_sha": _git_sha(),
            "config": config,
            "groups": os.environ.get("BENCH_GROUPS", ""),
            "e": os.environ.get("BENCH_E", ""),
            "sm": os.environ.get("BENCH_SM", ""),
        })
        _ledger_append(rec)
    print(json.dumps(out))


def probe_main() -> None:
    """Tiny child: report the default platform (and that it can compute)."""
    plan = os.environ.get("BENCH_FAKE_PROBE_PLAN")
    if plan:
        # Test hook (tests/test_bench.py): script the probe outcomes to
        # simulate a wedged-then-recovered tunnel.  Each probe consumes
        # one comma-separated step ("timeout" hangs until the parent's
        # timeout kills it; anything else is reported as the platform),
        # tracked in a state file since probes are separate processes.
        state = os.environ["BENCH_FAKE_PROBE_STATE"]
        try:
            with open(state) as f:
                i = int(f.read().strip() or "0")
        except OSError:
            i = 0
        with open(state, "w") as f:
            f.write(str(i + 1))
        steps = plan.split(",")
        step = steps[min(i, len(steps) - 1)]
        if step == "timeout":
            time.sleep(3600)
        plat, _, backend = step.partition(":")
        print(json.dumps({"probe": plat, "backend": backend or plat,
                          "devices": 1}))
        return
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    platform = "tpu" if d.platform == "axon" else d.platform
    # The raw backend name ("axon" for the remote-TPU tunnel) lets the
    # late-recovery ladder pin its children to this exact platform —
    # pinning "tpu" fails there, and unpinned children hang if the
    # tunnel wedges again between probe and rung.
    print(json.dumps({"probe": platform, "backend": d.platform,
                      "devices": len(jax.devices())}))


# ---------------------------------------------------------------------------
# Parent: bounded attempts, guaranteed JSON + exit 0.
# ---------------------------------------------------------------------------


def _attempt(platform: str, timeout_s: float, extra_env: dict | None = None,
             label: str = "", mode: str = "1") -> dict | None:
    """Run one child attempt; return its parsed JSON dict or None.

    Failures are RECORDED, not fatal: the returncode / timeout / missing
    JSON is logged per attempt so a device fault at one ladder shape
    localizes instead of erasing the round's evidence."""
    env = dict(os.environ, BENCH_CHILD=mode)
    if platform:
        env["BENCH_PLATFORM"] = platform
        # Must also be in the env BEFORE the child's sitecustomize imports
        # jax — the in-child config.update alone is a no-op if anything
        # initializes a backend at import time.
        env["JAX_PLATFORMS"] = platform
    if extra_env:
        env.update({k: str(v) for k, v in extra_env.items()})
    label = label or platform or "default"
    _log(f"bench parent: attempt[{label}] (timeout {timeout_s:.0f}s)")
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, stdout=subprocess.PIPE, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _log(f"bench parent: attempt[{label}] TIMED OUT after "
             f"{timeout_s:.0f}s")
        return None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and ("metric" in parsed
                                         or "probe" in parsed):
            return parsed
    _log(f"bench parent: attempt[{label}] rc={r.returncode}, no JSON")
    return None


def _emit(parsed: dict) -> None:
    print(json.dumps(parsed))


def main() -> None:
    """Parent: fault-localizing attempt ladder, guaranteed JSON + exit 0.

    Plan (VERDICT r2 task 1):
      1. Probe the default platform (the remote-TPU tunnel) with a SHORT
         timeout — a wedged tunnel hangs device init indefinitely, and
         burning the full attempt budget on it erased round 2's evidence.
      2. If the probe says tpu: run the G-ladder smallest-first
         (1k → 10k → 100k), each shape its own bounded child; retry
         failed shapes in a second pass; headline = largest success.
      3. Durable-path child on cpu (host-runtime benchmark, not device).
      4. If no TPU result at all: cpu fallback for the headline.
    """
    timeout_s = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "420"))
    pinned = os.environ.get("BENCH_PLATFORM", "")
    if pinned:
        parsed = _attempt(pinned, timeout_s)
        if parsed:
            _emit(parsed)
            return
        _log("bench parent: pinned attempt failed")
        _emit({"metric": "raft_commits_per_sec", "value": 0.0,
               "unit": "commits/s", "vs_baseline": 0.0, "platform": "none"})
        return

    # Overall wall budget: without it, a live-but-degraded tunnel that
    # times out EVERY ladder child would stretch the serial plan past the
    # driver's own deadline and reproduce the round-1 rc=124/no-JSON
    # failure.  The fallback reserve guarantees the cpu headline always
    # has room to run.
    budget_s = float(os.environ.get("BENCH_TOTAL_BUDGET_S", "1800"))
    t_start = time.monotonic()
    fallback_reserve = timeout_s + 90

    def remaining() -> float:
        return budget_s - (time.monotonic() - t_start)

    # -- 1. platform probe (twice: the tunnel can flake transiently).
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "150"))
    probe = None
    for i in range(2):
        probe = _attempt("", probe_timeout, label=f"probe{i}", mode="probe")
        if probe:
            break
    platform = (probe or {}).get("probe", "none")
    _log(f"bench parent: default platform = {platform}")

    ladder_env = os.environ.get("BENCH_LADDER", "1000,10000,32768,100000")
    ladder = [int(x) for x in ladder_env.split(",") if x]
    results: dict = {}
    faults: dict = {}
    if probe and platform not in ("cpu", "none"):
        # -- 2. TPU G-ladder, two passes, smallest shape first.
        for pass_no in range(2):
            for G in ladder:
                if G in results:
                    continue
                if remaining() < fallback_reserve + 60:
                    faults.setdefault(G, []).append(
                        f"pass{pass_no}:budget-exhausted")
                    continue
                got = _attempt(
                    "", min(timeout_s, remaining() - fallback_reserve),
                    # No per-rung latency sweep: each extra shape costs
                    # ~2 slow tunnel compiles and timed out whole rungs;
                    # one dedicated latency child runs after the ladder.
                    extra_env={"BENCH_GROUPS": G,
                               "BENCH_SKIP_SWEEP": "1",
                               "BENCH_TICKS": os.environ.get(
                                   "BENCH_TICKS", "400")},
                    label=f"tpu-G{G}-p{pass_no}")
                if got and got.get("value", 0) > 0:
                    results[G] = got
                else:
                    faults.setdefault(G, []).append(
                        f"pass{pass_no}:"
                        + ("no-json-or-crash" if got is None else "zero"))
            if len(results) == len(ladder):
                break
        _log(f"bench parent: ladder results "
             f"{ {g: round(r['value'], 1) for g, r in results.items()} } "
             f"faults {faults}")


    # -- 3-tpu. durable-path child ON THE DEVICE (fused runtime: one
    # dispatch per cluster tick + per-peer WAL fsync barrier).  Runs
    # right after the ladder while the tunnel is known-good — this is
    # the round-5 headline evidence (VERDICT r4 task 2).
    durable_tpu = None
    if results and os.environ.get("BENCH_SKIP_DURABLE") != "1" \
            and remaining() > fallback_reserve + 120:
        durable_tpu = _attempt(
            "", min(timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "durable",
                       "BENCH_DURABLE_MODE": "fused",
                       # Measured best host shape (bench_logs r5 with
                       # the C++ apply plane): E=64 beats 32 (768k vs
                       # 525k commits/s) and 128 (590k — WAL bytes
                       # dominate past the framing amortization).
                       "BENCH_E": os.environ.get("BENCH_E", "64"),
                       # Multi-step dispatch: the on-device durable
                       # tick is dispatch-overhead-bound through the
                       # tunnel (r5: 1219 ms/tick at G=1000); S steps
                       # per dispatch amortize it S-fold at the cost
                       # of S x device compute (cheap there).  CPU
                       # measurement: -13% throughput, p99 220->143ms.
                       "RAFTSQL_FUSED_STEPS": os.environ.get(
                           "RAFTSQL_FUSED_STEPS",
                           os.environ.get("BENCH_TPU_STEPS", "8"))},
            label="durable-tpu-fused")

    # -- 3. durable-path children (host runtime measured on cpu):
    # the per-peer RaftNode mode (history-comparable) and the fused
    # one-dispatch mode (the round-5 headline shape) — both recorded
    # even when the device is unreachable.
    durable = None
    if os.environ.get("BENCH_SKIP_DURABLE") != "1" \
            and remaining() > fallback_reserve + 120:
        durable = _attempt(
            "cpu", min(timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "durable",
                       "BENCH_DURABLE_MODE": "node"},
            label="durable-cpu")
    durable_fused = None

    # -- 3a'. end-to-end HTTP child (BASELINE config 1): the 3-process
    # Procfile cluster over real HTTP PUT/GET — the one configuration
    # the reference actually ships (VERDICT r3 task 3).
    httpc = None
    if os.environ.get("BENCH_SKIP_HTTP") != "1" \
            and remaining() > fallback_reserve + 150:
        # 2x the per-attempt timeout: the child now measures two rungs
        # (16-client latency point + high-concurrency throughput point),
        # each with its own cluster bring-up.
        httpc = _attempt(
            "cpu", min(2 * timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "http"}, label="http-cpu")

    # -- 3a''. config-1 ON THE DEVICE (VERDICT r4 missing item 4): ONE
    # fused server process inheriting the tunnel platform, driven over
    # real HTTP — the full client-visible stack with the consensus step
    # on the chip.  Single-process only (the tunnel is single-client),
    # and only once the ladder proved the tunnel good.
    http_tpu = None
    if results and os.environ.get("BENCH_SKIP_HTTP") != "1" \
            and remaining() > fallback_reserve + 460:
        # The guard covers the rung's worst case (360s device bring-up
        # + measurement); launching with less would kill the child
        # mid-compile and burn the tail budget for zero evidence.
        http_tpu = _attempt(
            "cpu", min(2 * timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "http", "BENCH_HTTP_DEVICE": "1",
                       "BENCH_HTTP_CLIENTS": "0",
                       "BENCH_HTTP_CLIENTS_HI": "0"},
            label="http-tpu-fused")

    # -- 3a. late re-probe (VERDICT r3 task 8): a tunnel that was wedged
    # during the early probes but recovered mid-budget was never noticed
    # — round 3 lost its TPU headline to exactly this.  If the ladder
    # produced nothing and budget remains after the (device-independent)
    # durable child, probe once more and rerun the rungs smallest-first.
    if not results and remaining() > fallback_reserve + 60:
        probe = _attempt("", probe_timeout, label="probe-late", mode="probe")
        late_platform = (probe or {}).get("probe", "none")
        _log(f"bench parent: late re-probe platform = {late_platform}")
        if probe and late_platform not in ("cpu", "none"):
            platform = late_platform
            # Pin rung children to the probed RAW backend (e.g. "axon"):
            # an unpinned child re-resolves the default platform and
            # hangs all over again if the tunnel re-wedges; the pin also
            # lets the stubbed-parent test drive this path on cpu.
            late_backend = (probe or {}).get("backend", "")
            for G in ladder:
                if remaining() < fallback_reserve + 60:
                    faults.setdefault(G, []).append("late:budget-exhausted")
                    continue
                got = _attempt(
                    late_backend,
                    min(timeout_s, remaining() - fallback_reserve),
                    extra_env={"BENCH_GROUPS": G, "BENCH_SKIP_SWEEP": "1",
                               "BENCH_TICKS": os.environ.get(
                                   "BENCH_TICKS", "400")},
                    label=f"tpu-G{G}-late")
                if got and got.get("value", 0) > 0:
                    results[G] = got
                else:
                    faults.setdefault(G, []).append(
                        "late:" + ("no-json-or-crash" if got is None
                                   else "zero"))
            _log(f"bench parent: late ladder results "
                 f"{ {g: round(r['value'], 1) for g, r in results.items()} }"
                 f" faults {faults}")

    # -- 3a''. fused durable on cpu (the round-5 headline shape) —
    # AFTER the late re-probe so a recoverable TPU headline always
    # outranks this secondary CPU rung in the budget.
    if os.environ.get("BENCH_SKIP_DURABLE") != "1" \
            and remaining() > fallback_reserve + 120:
        durable_fused = _attempt(
            "cpu", min(timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "durable",
                       "BENCH_DURABLE_MODE": "fused",
                       "BENCH_E": os.environ.get("BENCH_E", "64"),
                       # Interleaved A/B at G=1000/E=64 on one core:
                       # S=4 wins both pairs (625/681k vs 543/630k) —
                       # bigger per-dispatch WAL batches.
                       "RAFTSQL_FUSED_STEPS": os.environ.get(
                           "RAFTSQL_FUSED_STEPS", "4")},
            label="durable-cpu-fused")

    # -- 3b. latency child on the device: ONE small shape (G=1024, E=16)
    # where the 3-tick pipeline meets the <2 ms p50 target; its own
    # child so a fault cannot cost the headline and the ladder rungs
    # stay single-shape (sweep compiles timed out rung
    # children).  Runs AFTER the cheap durable child so a slow sweep
    # cannot burn the budget the durable evidence needs.
    latc = None
    if results and remaining() > fallback_reserve + 180 \
            and os.environ.get("BENCH_SKIP_SWEEP") != "1":
        latc = _attempt(
            "", min(timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "latency", "BENCH_GROUPS": "1024",
                       "BENCH_REPEATS": "2",
                       "BENCH_LAT_CURVE": os.environ.get(
                           "BENCH_LAT_CURVE", "1000,10000,100000")},
            label="latency-G1024")

    # -- 3c. commit-rule race on the device (point vs windowed vs
    # pallas-compiled), at a mid-ladder shape so a kernel fault in one
    # rule cannot cost the headline.  Runs LAST of the children: the
    # headline, latency-target, and durable evidence all outrank it
    # under budget pressure.
    rules = None
    if results and remaining() > fallback_reserve + 240 \
            and os.environ.get("BENCH_SKIP_RULES") != "1":
        rules_g = min(max(results), 10_000)
        rules = _attempt(
            "", min(timeout_s, remaining() - fallback_reserve),
            extra_env={"BENCH_CONFIG": "rules", "BENCH_GROUPS": rules_g,
                       "BENCH_TICKS": "200", "BENCH_REPEATS": "2",
                       "BENCH_RULES_SET": "point,windowed"},
            label=f"rules-G{rules_g}")
        # Pallas in its own child: a compile hang there (observed at
        # P=15 on the device) burns only this attempt's timeout.
        if remaining() > fallback_reserve + 240:
            pall = _attempt(
                "", min(timeout_s // 2, remaining() - fallback_reserve),
                extra_env={"BENCH_CONFIG": "rules",
                           "BENCH_GROUPS": rules_g,
                           "BENCH_TICKS": "200", "BENCH_REPEATS": "2",
                           "BENCH_RULES_SET": "pallas"},
                label=f"rules-pallas-G{rules_g}")
            prow = (pall or {}).get("rules") or {}
            if rules and rules.get("rules"):
                for label, row in rules["rules"].items():
                    row.update(prow.get(label,
                                        {"pallas": "fault: no result"}))



    def _record_durable_fused(parsed: dict) -> None:
        if not durable_fused:
            return
        parsed["durable_fused_commits_per_s"] = durable_fused.get("value")
        parsed["durable_fused_tick_ms"] = \
            durable_fused.get("durable_tick_ms")
        parsed["durable_fused_lat"] = durable_fused.get("durable_lat")
        parsed["durable_fused_sm"] = durable_fused.get("durable_sm")

    if results:
        # Headline = best commits/s across the ladder (the throughput
        # curve peaks near G=32k and flattens; "largest G that ran" was
        # leaving ~30% on the table), with the full ladder recorded.
        bestG = max(results, key=lambda g: results[g]["value"])
        parsed = results[bestG]
        parsed["headline_groups"] = bestG
        parsed["ladder"] = {
            str(g): (round(results[g]["value"], 1) if g in results
                     else "fault: " + ";".join(faults.get(g, ["?"])))
            for g in ladder}
        if rules:
            parsed["rules"] = rules.get("rules")
        if latc:
            parsed["lat"] = latc.get("lat")
            # 0.0 means "sweep measured nothing", not a passed target.
            parsed["p50_light_ms"] = latc.get("value") or None
        if durable:
            parsed["durable_commits_per_s"] = durable.get("value")
            parsed["durable_tick_ms"] = durable.get("durable_tick_ms")
            parsed["durable_lat"] = durable.get("durable_lat")
            parsed["durable_sm"] = durable.get("durable_sm")
        _record_durable_fused(parsed)
        if durable_tpu:
            parsed["durable_tpu_commits_per_s"] = durable_tpu.get("value")
            parsed["durable_tpu_tick_ms"] = \
                durable_tpu.get("durable_tick_ms")
            parsed["durable_tpu_lat"] = durable_tpu.get("durable_lat")
            parsed["durable_tpu_platform"] = durable_tpu.get("platform")
            parsed["durable_tpu_sm"] = durable_tpu.get("durable_sm")
        if httpc:
            parsed["http_req_per_s"] = httpc.get("value")
            for k in ("http_lat", "http_lat_hi", "http_lat_fused",
                      "http_lat_fused_hi"):
                parsed[k] = httpc.get(k)
            parsed["http_cpu_count"] = httpc.get("cpu_count")
        if http_tpu:
            parsed["http_tpu_req_per_s"] = http_tpu.get("value")
            parsed["http_lat_fused_tpu"] = \
                http_tpu.get("http_lat_fused_tpu")
        _emit(parsed)
        return

    # -- 4. cpu fallback headline.
    _log("bench parent: no TPU result; falling back to cpu headline")
    parsed = _attempt("cpu", max(min(timeout_s, remaining() - 30), 120))
    if parsed:
        # Record WHY the platform is cpu: "timeout" = both device-init
        # probes hung (a wedged remote-TPU tunnel, the round-1 failure
        # mode), vs a probed-alive device whose ladder rungs then all
        # faulted (recorded separately in tpu_faults).
        parsed["tpu_probe"] = platform if probe else "timeout"
        if faults:
            parsed["tpu_faults"] = {str(g): v for g, v in faults.items()}
        if durable:
            parsed["durable_commits_per_s"] = durable.get("value")
            parsed["durable_tick_ms"] = durable.get("durable_tick_ms")
            parsed["durable_lat"] = durable.get("durable_lat")
            parsed["durable_sm"] = durable.get("durable_sm")
        _record_durable_fused(parsed)
        if httpc:
            parsed["http_req_per_s"] = httpc.get("value")
            for k in ("http_lat", "http_lat_hi", "http_lat_fused",
                      "http_lat_fused_hi"):
                parsed[k] = httpc.get(k)
            parsed["http_cpu_count"] = httpc.get("cpu_count")
        # Clearly-labeled history, not a headline: the newest committed
        # TPU_RUNS.jsonl entry, so a wedged tunnel leaves a citable
        # last-known-good TPU result in the official record.
        last_good = _ledger_last_good()
        if last_good:
            parsed["last_good_tpu"] = last_good
        _emit(parsed)
        return
    _log("bench parent: all attempts failed")
    _emit({"metric": "raft_commits_per_sec", "value": 0.0,
           "unit": "commits/s", "vs_baseline": 0.0, "platform": "none"})


if __name__ == "__main__":
    mode = os.environ.get("BENCH_CHILD")
    if mode == "probe":
        probe_main()
    elif mode:
        child_main()
    else:
        main()
