"""Benchmark harness — the five BASELINE.json configs on one chip.

Headline (default, what the driver records): committed log entries per
second across N raft groups, using the fused whole-cluster step
(core/cluster.py) — P peers x G groups advanced per device tick, proposals
flowing at the flow-control limit, commits counted on device so only one
scalar crosses the host boundary per timed run.

The reference (chzchzchz/raftsql) publishes no numbers (BASELINE.md); the
baseline used for `vs_baseline` is the driver-set north star of 1e8
commits/sec (100k groups x 1k proposals/sec each, BASELINE.json).

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Extra detail (per-config runs, latency estimate) goes to stderr.

Environment knobs:
  BENCH_CONFIG   headline | quorum | elections | commit_scan | multichip
                 | all          (default headline)
  BENCH_GROUPS / BENCH_PEERS / BENCH_TICKS / BENCH_REPEATS
  BENCH_PLATFORM cpu|tpu        (override the captured jax platform)
  BENCH_PROFILE  <dir>          (wrap timed runs in jax.profiler.trace)
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import sys
import time

import jax

if os.environ.get("BENCH_PLATFORM"):
    # This environment's sitecustomize imports jax before us, so the
    # JAX_PLATFORMS env var is already captured; update the live config.
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

import jax.numpy as jnp

from raftsql_tpu.config import LEADER, RaftConfig
from raftsql_tpu.core.cluster import (cluster_step, empty_cluster_inbox,
                                      init_cluster_state)

NORTH_STAR_COMMITS_PER_SEC = 1.0e8


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _profiled():
    d = os.environ.get("BENCH_PROFILE")
    return jax.profiler.trace(d) if d else contextlib.nullcontext()


def make_bench_run(cfg: RaftConfig, num_ticks: int):
    """Jitted: scan `num_ticks` cluster ticks; return (commit delta, mean
    in-flight depth) — both device scalars.

    Commit progress per group = max over peers of the commit index (every
    peer converges to it; max is the entries durably quorum-committed).
    The in-flight depth feeds Little's-law latency: W = L / lambda.
    """

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(states, inboxes, prop_n):
        commit0 = jnp.sum(jnp.max(states.commit, axis=0))

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop_n)
            depth = jnp.mean((jnp.max(st.log_len, axis=0)
                              - jnp.max(st.commit, axis=0)).astype(jnp.float32))
            return (st, ib), depth

        (states, inboxes), depths = jax.lax.scan(
            body, (states, inboxes), None, length=num_ticks)
        committed = jnp.sum(jnp.max(states.commit, axis=0)) - commit0
        return states, inboxes, committed, jnp.mean(depths)

    return run


def bench_throughput(groups: int, peers: int, ticks: int, repeats: int,
                     saturate: bool = True) -> float:
    """Commits/sec for a G x P fused cluster under saturating load."""
    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    # Build the initial state ON device in one compiled program — at 100k
    # groups the eager per-leaf host->device transfers are the slow (and,
    # through a remote-device tunnel, fragile) path.
    states, inboxes = jax.jit(
        lambda: (init_cluster_state(cfg), empty_cluster_inbox(cfg)))()
    load = cfg.max_entries_per_msg if saturate else 0
    full = jnp.full((cfg.num_peers, cfg.num_groups), load, jnp.int32)

    run = make_bench_run(cfg, ticks)
    warm = make_bench_run(cfg, 4 * cfg.election_ticks)

    # Warmup: elect leaders everywhere + trigger both compiles.
    states, inboxes, _, _ = warm(states, inboxes, full * 0)
    states, inboxes, c, _ = run(states, inboxes, full)
    jax.block_until_ready(c)

    best, best_lat = 0.0, float("inf")
    total_committed = 0
    lat_ms = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        with _profiled():
            states, inboxes, committed, depth = run(states, inboxes, full)
            committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        total_committed += committed
        rate = committed / dt
        # Little's law: mean propose->commit latency = depth / (per-group
        # commit rate); depth is the mean uncommitted in-flight window.
        lat_ms = (float(depth) * groups / rate * 1e3) if rate else 0.0
        best = max(best, rate)
        best_lat = min(best_lat, lat_ms)
        _log(f"  {committed} commits in {dt:.3f}s -> {rate:,.0f} commits/s "
             f"({rate / groups:,.1f}/group/s, est. mean latency "
             f"{lat_ms:.2f} ms)")
    if saturate and total_committed == 0:
        raise RuntimeError("benchmark committed nothing — engine stalled")
    if best_lat < float("inf"):
        _log(f"  best: {best:,.0f} commits/s, est. mean propose->commit "
             f"latency {best_lat:.2f} ms (saturated queueing)")
    return best


def bench_elections(groups: int, peers: int, repeats: int) -> float:
    """BASELINE config 3: randomized leader election at G x P.

    Measures cold-start elections/sec: from a fresh (all-follower) state,
    ticks until every group has a leader, repeated; value = groups elected
    per second of device time.
    """
    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    T = 4 * cfg.election_ticks

    @jax.jit
    def elect(seed):
        states = init_cluster_state(cfg, seed=0)
        # Re-randomize timers per repeat by folding the seed into rng.
        states = states._replace(tick=states.tick + seed)
        inboxes = empty_cluster_inbox(cfg)
        prop = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop)
            return (st, ib), None

        (states, _), _ = jax.lax.scan(body, (states, inboxes), None,
                                      length=T)
        return jnp.sum(jnp.any(states.role == LEADER, axis=0))

    elected = int(elect(jnp.asarray(0, jnp.int32)))  # compile + check
    best = 0.0
    for r in range(repeats):
        t0 = time.perf_counter()
        elected = int(jax.block_until_ready(elect(jnp.asarray(r, jnp.int32))))
        dt = time.perf_counter() - t0
        _log(f"  elected {elected}/{groups} leaders in {dt:.3f}s "
             f"({T} ticks) -> {elected / dt:,.0f} elections/s")
        best = max(best, elected / dt)
    return best


def bench_commit_scan(groups: int, repeats: int) -> float:
    """BASELINE config 4: the commit-index kernel alone at 100k groups.

    Measures group-commit-scans/sec of `windowed_commit_index` (the full
    masked prefix scan over the term ring) on random match/ring state.
    """
    from raftsql_tpu.ops.commit_scan import windowed_commit_index

    W, P = 64, 5
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    log_len = jax.random.randint(ks[0], (groups,), 0, W, dtype=jnp.int32)
    match = jnp.minimum(
        jax.random.randint(ks[1], (groups, P), 0, W, dtype=jnp.int32),
        log_len[:, None])
    log_term = jax.random.randint(ks[2], (groups, W), 1, 4, dtype=jnp.int32)
    commit = jnp.maximum(log_len - 8, 0)
    term = jnp.full((groups,), 3, jnp.int32)
    is_leader = jnp.ones((groups,), bool)

    @jax.jit
    def kernel(match, log_term, log_len, commit, term):
        return windowed_commit_index(match, log_term, log_len, commit,
                                     term, is_leader, quorum=3, window=W)

    out = jax.block_until_ready(
        kernel(match, log_term, log_len, commit, term))
    iters = 50
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = kernel(match, log_term, log_len, commit, term)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rate = groups * iters / dt
        _log(f"  {iters} x {groups}-group commit scans in {dt:.3f}s -> "
             f"{rate:,.0f} scans/s")
        best = max(best, rate)
    return best


def bench_multichip(ticks: int, repeats: int) -> float:
    """BASELINE config 5: groups sharded over the device mesh, peer
    message exchange riding `all_to_all` (parallel/sharded.py)."""
    from raftsql_tpu.parallel.sharded import (make_mesh,
                                              make_sharded_cluster_run,
                                              shard_cluster_arrays)

    n = len(jax.devices())
    pp = 2 if n % 2 == 0 and n > 1 else 1
    gg = n // pp
    groups = int(os.environ.get("BENCH_GROUPS", 8192 * gg))
    groups -= groups % gg
    cfg = RaftConfig(num_groups=groups, num_peers=2 * pp if pp > 1 else 3,
                     log_window=64, max_entries_per_msg=8,
                     tick_interval_s=0.0)
    mesh = make_mesh(pp, gg)
    _log(f"  mesh {pp}x{gg} over {n} devices, {groups} groups x "
         f"{cfg.num_peers} peers")
    states = init_cluster_state(cfg)
    inboxes = empty_cluster_inbox(cfg)
    full = jnp.full((ticks, cfg.num_peers, cfg.num_groups),
                    cfg.max_entries_per_msg, jnp.int32)
    states, inboxes = shard_cluster_arrays(mesh, states, inboxes)

    run = make_sharded_cluster_run(cfg, mesh, ticks)
    states, inboxes, c = run(states, inboxes, full * 0)   # warmup/elect
    states, inboxes, c = run(states, inboxes, full)
    jax.block_until_ready(c)

    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        states, inboxes, committed = run(states, inboxes, full)
        committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        _log(f"  {committed} commits in {dt:.3f}s -> "
             f"{committed / dt:,.0f} commits/s")
        best = max(best, committed / dt)
    return best


def main() -> None:
    config = os.environ.get("BENCH_CONFIG", "headline")
    groups = int(os.environ.get("BENCH_GROUPS", 100_000))
    peers = int(os.environ.get("BENCH_PEERS", 3))
    ticks = int(os.environ.get("BENCH_TICKS", 400))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))
    _log(f"bench[{config}]: platform={jax.devices()[0].platform} "
         f"devices={len(jax.devices())}")

    if config == "all":
        results = {}
        _log("== config 2: 1k x 3 quorum replication ==")
        results["quorum_1k_x3"] = bench_throughput(1000, 3, ticks, repeats)
        _log("== config 3: 10k x 5 elections ==")
        results["elections_10k_x5"] = bench_elections(10_000, 5, repeats)
        _log("== config 4: 100k-group commit scan ==")
        results["commit_scan_100k"] = bench_commit_scan(100_000, repeats)
        _log("== config 5: mesh-sharded cluster ==")
        results["multichip"] = bench_multichip(ticks, repeats)
        _log("== headline: G x P saturated throughput ==")
        results["headline"] = bench_throughput(groups, peers, ticks, repeats)
        for k, v in results.items():
            _log(f"{k}: {v:,.0f}/s")
        value = results["headline"]
    elif config == "quorum":
        value = bench_throughput(1000, 3, ticks, repeats)
    elif config == "elections":
        value = bench_elections(int(os.environ.get("BENCH_GROUPS", 10_000)),
                                5, repeats)
    elif config == "commit_scan":
        value = bench_commit_scan(groups, repeats)
    elif config == "multichip":
        value = bench_multichip(ticks, repeats)
    else:
        value = bench_throughput(groups, peers, ticks, repeats)

    print(json.dumps({
        "metric": "raft_commits_per_sec",
        "value": round(value, 1),
        "unit": "commits/s",
        "vs_baseline": round(value / NORTH_STAR_COMMITS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
