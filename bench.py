"""Benchmark harness — the five BASELINE.json configs on one chip.

Headline (default, what the driver records): committed log entries per
second across N raft groups, using the fused whole-cluster step
(core/cluster.py) — P peers x G groups advanced per device tick, proposals
flowing at the flow-control limit, commits counted on device so only one
scalar crosses the host boundary per timed run.

Latency is MEASURED, not estimated: the commit trajectory [T, G] is kept on
device, `ops.commit_scan.commit_latency_ticks` finds the first tick at
which each group commits the batch appended on tick 0, and p50/p99 ticks x
measured tick wall-time give propose→commit milliseconds (stderr + README).
Groups that never commit the target inside the run are excluded from the
percentiles and reported as a censored count.

Prints exactly one JSON line on stdout and ALWAYS exits 0:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "platform": ...}

Robustness model (the round-1 failure was rc=1/rc=124 with no number at
all): the process runs as a PARENT that never imports a jax backend.  Each
attempt is a CHILD subprocess under a hard timeout — first on the default
platform (the remote-TPU "axon" tunnel when alive), then pinned to cpu.  A
wedged or UNAVAILABLE tunnel therefore costs one bounded timeout and the
driver still gets a real measured number from the cpu attempt.

The reference (chzchzchz/raftsql) publishes no numbers (BASELINE.md); the
baseline used for `vs_baseline` is the driver-set north star of 1e8
commits/sec (100k groups x 1k proposals/sec each, BASELINE.json).

Environment knobs:
  BENCH_CONFIG   headline | quorum | elections | commit_scan | multichip
                 | all          (default headline)
  BENCH_GROUPS / BENCH_PEERS / BENCH_TICKS / BENCH_REPEATS
  BENCH_PLATFORM cpu|tpu        (parent: single attempt on this platform)
  BENCH_ATTEMPT_TIMEOUT_S       (default 420, per child attempt)
  BENCH_PROFILE  <dir>          (wrap timed runs in jax.profiler.trace)
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import subprocess
import sys
import time

NORTH_STAR_COMMITS_PER_SEC = 1.0e8


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Child: one measurement attempt on one platform.
# ---------------------------------------------------------------------------


def _profiled():
    import jax
    d = os.environ.get("BENCH_PROFILE")
    return jax.profiler.trace(d) if d else contextlib.nullcontext()


def make_bench_run(cfg, num_ticks: int):
    """Jitted: scan `num_ticks` cluster ticks; returns device scalars
    (commit delta, [p50, p99] latency ticks, number of groups that
    committed the tick-0 batch).

    Latency: the proposals appended during tick 0 of the run define a
    per-group target index (max log_len after tick 0); the commit
    trajectory's first crossing of that target is the measured
    propose→commit tick count (ops/commit_scan.py).  Groups that never
    cross inside the run are right-censored: excluded from percentiles,
    counted separately.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.core.cluster import cluster_step
    from raftsql_tpu.ops.commit_scan import (commit_latency_ticks,
                                             running_commit)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(states, inboxes, prop_n):
        commit0 = jnp.max(states.commit, axis=0)                    # [G]

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop_n)
            return (st, ib), (jnp.max(st.commit, axis=0),
                              jnp.max(st.log_len, axis=0))

        (states, inboxes), (ctraj, ltraj) = jax.lax.scan(
            body, (states, inboxes), None, length=num_ticks)
        committed = jnp.sum(ctraj[-1] - commit0)
        first = commit_latency_ticks(running_commit(ctraj), ltraj[0])
        ok = first < num_ticks                                      # [G]
        n_ok = jnp.sum(ok)
        lats = jnp.sort(jnp.where(ok, (first + 1).astype(jnp.float32),
                                  jnp.inf))
        G = lats.shape[0]

        def q(p):
            i = (p * (n_ok.astype(jnp.float32) - 1.0)).astype(jnp.int32)
            return lats[jnp.clip(i, 0, G - 1)]

        pct = jnp.where(n_ok > 0, jnp.stack([q(0.5), q(0.99)]),
                        jnp.full((2,), jnp.inf))
        return states, inboxes, committed, pct, n_ok

    return run


def bench_throughput(groups: int, peers: int, ticks: int, repeats: int,
                     saturate: bool = True) -> float:
    """Commits/sec for a G x P fused cluster under saturating load."""
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core.cluster import (empty_cluster_inbox,
                                          init_cluster_state)

    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    # Build the initial state ON device in one compiled program — at 100k
    # groups the eager per-leaf host->device transfers are the slow (and,
    # through a remote-device tunnel, fragile) path.
    states, inboxes = jax.jit(
        lambda: (init_cluster_state(cfg), empty_cluster_inbox(cfg)))()
    load = cfg.max_entries_per_msg if saturate else 0
    full = jnp.full((cfg.num_peers, cfg.num_groups), load, jnp.int32)

    run = make_bench_run(cfg, ticks)
    warm = make_bench_run(cfg, 4 * cfg.election_ticks)

    # Warmup: elect leaders everywhere + trigger both compiles.
    states, inboxes, _, _, _ = warm(states, inboxes, full * 0)
    states, inboxes, c, _, _ = run(states, inboxes, full)
    jax.block_until_ready(c)

    best, best_p50, best_p99 = 0.0, float("inf"), float("inf")
    total_committed = 0
    for _ in range(repeats):
        t0 = time.perf_counter()
        with _profiled():
            states, inboxes, committed, pct, n_ok = run(
                states, inboxes, full)
            committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        total_committed += committed
        rate = committed / dt
        tick_ms = dt / ticks * 1e3
        n_ok = int(n_ok)
        if n_ok:
            p50, p99 = float(pct[0]) * tick_ms, float(pct[1]) * tick_ms
            lat_msg = (f"measured propose->commit p50={p50:.3f} ms "
                       f"p99={p99:.3f} ms ({float(pct[0]):.0f}/"
                       f"{float(pct[1]):.0f} ticks x {tick_ms:.4f} ms/tick, "
                       f"{groups - n_ok} censored)")
            if p50 < best_p50:
                best_p50, best_p99 = p50, p99
        else:
            lat_msg = "latency n/a (no group committed the marked batch)"
        _log(f"  {committed} commits in {dt:.3f}s -> {rate:,.0f} commits/s "
             f"({rate / groups:,.1f}/group/s); {lat_msg}")
        best = max(best, rate)
    if saturate and total_committed == 0:
        raise RuntimeError("benchmark committed nothing — engine stalled")
    if best_p50 < float("inf"):
        _log(f"  best: {best:,.0f} commits/s, measured propose->commit "
             f"p50={best_p50:.3f} ms p99={best_p99:.3f} ms (saturated load)")
    return best


def bench_elections(groups: int, peers: int, repeats: int) -> float:
    """BASELINE config 3: randomized leader election at G x P.

    Measures cold-start elections/sec: from a fresh (all-follower) state,
    ticks until every group has a leader, repeated; value = groups elected
    per second of device time.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import LEADER, RaftConfig
    from raftsql_tpu.core.cluster import (cluster_step, empty_cluster_inbox,
                                          init_cluster_state)

    cfg = RaftConfig(num_groups=groups, num_peers=peers, log_window=64,
                     max_entries_per_msg=8, tick_interval_s=0.0)
    T = 4 * cfg.election_ticks

    @jax.jit
    def elect(seed):
        states = init_cluster_state(cfg, seed=0)
        # Re-randomize timers per repeat by folding the seed into rng.
        states = states._replace(tick=states.tick + seed)
        inboxes = empty_cluster_inbox(cfg)
        prop = jnp.zeros((cfg.num_peers, cfg.num_groups), jnp.int32)

        def body(carry, _):
            st, ib = carry
            st, ib, _ = cluster_step(cfg, st, ib, prop)
            return (st, ib), None

        (states, _), _ = jax.lax.scan(body, (states, inboxes), None,
                                      length=T)
        return jnp.sum(jnp.any(states.role == LEADER, axis=0))

    elected = int(elect(jnp.asarray(0, jnp.int32)))  # compile + check
    best = 0.0
    for r in range(repeats):
        t0 = time.perf_counter()
        elected = int(jax.block_until_ready(elect(jnp.asarray(r, jnp.int32))))
        dt = time.perf_counter() - t0
        _log(f"  elected {elected}/{groups} leaders in {dt:.3f}s "
             f"({T} ticks) -> {elected / dt:,.0f} elections/s")
        best = max(best, elected / dt)
    return best


def bench_commit_scan(groups: int, repeats: int) -> float:
    """BASELINE config 4: the commit-index kernel alone at 100k groups.

    Measures group-commit-scans/sec of `windowed_commit_index` (the full
    masked prefix scan over the term ring) on random match/ring state.
    """
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.ops.commit_scan import windowed_commit_index

    W, P = 64, 5
    key = jax.random.PRNGKey(0)
    ks = jax.random.split(key, 4)
    log_len = jax.random.randint(ks[0], (groups,), 0, W, dtype=jnp.int32)
    match = jnp.minimum(
        jax.random.randint(ks[1], (groups, P), 0, W, dtype=jnp.int32),
        log_len[:, None])
    log_term = jax.random.randint(ks[2], (groups, W), 1, 4, dtype=jnp.int32)
    commit = jnp.maximum(log_len - 8, 0)
    term = jnp.full((groups,), 3, jnp.int32)
    is_leader = jnp.ones((groups,), bool)

    @jax.jit
    def kernel(match, log_term, log_len, commit, term):
        return windowed_commit_index(match, log_term, log_len, commit,
                                     term, is_leader, quorum=3, window=W)

    out = jax.block_until_ready(
        kernel(match, log_term, log_len, commit, term))
    iters = 50
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = kernel(match, log_term, log_len, commit, term)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        rate = groups * iters / dt
        _log(f"  {iters} x {groups}-group commit scans in {dt:.3f}s -> "
             f"{rate:,.0f} scans/s")
        best = max(best, rate)
    return best


def bench_multichip(ticks: int, repeats: int) -> float:
    """BASELINE config 5: groups sharded over the device mesh, peer
    message exchange riding `all_to_all` (parallel/sharded.py)."""
    import jax
    import jax.numpy as jnp

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.core.cluster import (empty_cluster_inbox,
                                          init_cluster_state)
    from raftsql_tpu.parallel.sharded import (make_mesh,
                                              make_sharded_cluster_run,
                                              shard_cluster_arrays)

    n = len(jax.devices())
    pp = 2 if n % 2 == 0 and n > 1 else 1
    gg = n // pp
    groups = int(os.environ.get("BENCH_GROUPS", 8192 * gg))
    groups -= groups % gg
    cfg = RaftConfig(num_groups=groups, num_peers=2 * pp if pp > 1 else 3,
                     log_window=64, max_entries_per_msg=8,
                     tick_interval_s=0.0)
    mesh = make_mesh(pp, gg)
    _log(f"  mesh {pp}x{gg} over {n} devices, {groups} groups x "
         f"{cfg.num_peers} peers")
    states = init_cluster_state(cfg)
    inboxes = empty_cluster_inbox(cfg)
    full = jnp.full((ticks, cfg.num_peers, cfg.num_groups),
                    cfg.max_entries_per_msg, jnp.int32)
    states, inboxes = shard_cluster_arrays(mesh, states, inboxes)

    run = make_sharded_cluster_run(cfg, mesh, ticks)
    states, inboxes, c = run(states, inboxes, full * 0)   # warmup/elect
    states, inboxes, c = run(states, inboxes, full)
    jax.block_until_ready(c)

    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        states, inboxes, committed = run(states, inboxes, full)
        committed = int(jax.block_until_ready(committed))
        dt = time.perf_counter() - t0
        _log(f"  {committed} commits in {dt:.3f}s -> "
             f"{committed / dt:,.0f} commits/s")
        best = max(best, committed / dt)
    return best


def run_config(config: str, cpu: bool) -> float:
    """Dispatch one BENCH_CONFIG; defaults scale down on cpu so the
    fallback path still finishes inside the driver's time budget."""
    groups = int(os.environ.get("BENCH_GROUPS", 4096 if cpu else 100_000))
    peers = int(os.environ.get("BENCH_PEERS", 3))
    ticks = int(os.environ.get("BENCH_TICKS", 120 if cpu else 400))
    repeats = int(os.environ.get("BENCH_REPEATS", 2 if cpu else 3))
    egroups = int(os.environ.get("BENCH_GROUPS", 2048 if cpu else 10_000))

    if config == "all":
        results = {}
        _log("== config 2: 1k x 3 quorum replication ==")
        results["quorum_1k_x3"] = bench_throughput(1000, 3, ticks, repeats)
        _log("== config 3: elections ==")
        results["elections"] = bench_elections(egroups, 5, repeats)
        _log("== config 4: commit scan ==")
        results["commit_scan"] = bench_commit_scan(
            20_000 if cpu else 100_000, repeats)
        _log("== config 5: mesh-sharded cluster ==")
        results["multichip"] = bench_multichip(ticks, repeats)
        _log("== headline: G x P saturated throughput ==")
        results["headline"] = bench_throughput(groups, peers, ticks, repeats)
        for k, v in results.items():
            _log(f"{k}: {v:,.0f}/s")
        return results["headline"]
    if config == "quorum":
        return bench_throughput(1000, 3, ticks, repeats)
    if config == "elections":
        return bench_elections(egroups, 5, repeats)
    if config == "commit_scan":
        return bench_commit_scan(groups, repeats)
    if config == "multichip":
        return bench_multichip(ticks, repeats)
    return bench_throughput(groups, peers, ticks, repeats)


def child_main() -> None:
    """One attempt: pin the requested platform, measure, print JSON."""
    import jax

    want = os.environ.get("BENCH_PLATFORM", "")
    if want:
        # sitecustomize imports jax before us, so JAX_PLATFORMS was already
        # captured from the env; update the live config.
        jax.config.update("jax_platforms", want)
    config = os.environ.get("BENCH_CONFIG", "headline")
    platform = jax.devices()[0].platform
    _log(f"bench[{config}]: platform={platform} "
         f"devices={len(jax.devices())}")
    value = run_config(config, cpu=platform == "cpu")
    print(json.dumps({
        "metric": "raft_commits_per_sec",
        "value": round(value, 1),
        "unit": "commits/s",
        "vs_baseline": round(value / NORTH_STAR_COMMITS_PER_SEC, 4),
        "platform": platform,
    }))


# ---------------------------------------------------------------------------
# Parent: bounded attempts, guaranteed JSON + exit 0.
# ---------------------------------------------------------------------------


def _attempt(platform: str, timeout_s: float) -> str | None:
    """Run one child attempt; return its JSON line or None."""
    env = dict(os.environ, BENCH_CHILD="1")
    if platform:
        env["BENCH_PLATFORM"] = platform
        # Must also be in the env BEFORE the child's sitecustomize imports
        # jax — the in-child config.update alone is a no-op if anything
        # initializes a backend at import time.
        env["JAX_PLATFORMS"] = platform
    label = platform or "default"
    _log(f"bench parent: attempt on platform={label} "
         f"(timeout {timeout_s:.0f}s)")
    try:
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, stdout=subprocess.PIPE, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _log(f"bench parent: attempt[{label}] timed out")
        return None
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            return line
    _log(f"bench parent: attempt[{label}] rc={r.returncode}, no JSON")
    return None


def main() -> None:
    timeout_s = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT_S", "420"))
    pinned = os.environ.get("BENCH_PLATFORM", "")
    # With an explicit platform: one attempt. Otherwise: default backend
    # (TPU when the tunnel is alive) first, cpu as the fallback.
    plans = [pinned] if pinned else ["", "cpu"]
    for platform in plans:
        line = _attempt(platform, timeout_s)
        if line:
            print(line)
            return
    _log("bench parent: all attempts failed")
    print(json.dumps({
        "metric": "raft_commits_per_sec",
        "value": 0.0,
        "unit": "commits/s",
        "vs_baseline": 0.0,
        "platform": "none",
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        child_main()
    else:
        main()
