"""Memory soak: sustained durable load with log compaction, RSS bounded.

VERDICT r4 task 8: storage/log.py grows without bound under parity
semantics (the reference's MemoryStorage, raft.go:129) — but the
framework HAS compaction; this soak proves the bounded-memory
configuration works at scale.  A FusedClusterNode runs saturated load
across G groups; every `--compact-every` ticks the runtime compacts to
(applied - keep); RSS is sampled each round and printed as a ledger.

Run (CPU or TPU; CPU shown):

    JAX_PLATFORMS=cpu python scripts/soak_memory.py \
        --groups 100000 --target-commits 10000000

Output: one line per round
  tick=N commits=M rss_mb=R plog_entries=K segments=S
and a final PASS/FAIL: RSS at end  <= --rss-budget-x times RSS after the
first round (steady state reached early), floors advanced, commits hit
the target.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def main() -> None:
    # Honor JAX_PLATFORMS against the axon sitecustomize, which captures
    # jax_platforms at interpreter start: without the live-config pin a
    # `JAX_PLATFORMS=cpu` run still inits the default (tunnel) backend
    # and hangs whenever the tunnel is wedged (the exact hazard
    # documented in tests/conftest.py and __graft_entry__.py).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)

    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=100_000)
    ap.add_argument("--peers", type=int, default=3)
    ap.add_argument("--e", type=int, default=8)
    ap.add_argument("--target-commits", type=int, default=10_000_000)
    ap.add_argument("--compact-every", type=int, default=4)
    ap.add_argument("--keep", type=int, default=64)
    ap.add_argument("--rss-budget-x", type=float, default=1.5)
    args = ap.parse_args()

    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.runtime.fused import FusedClusterNode

    cfg = RaftConfig(num_groups=args.groups, num_peers=args.peers,
                     log_window=32, max_entries_per_msg=args.e,
                     tick_interval_s=0.0)
    tmp = tempfile.mkdtemp(prefix="soak-")
    node = FusedClusterNode(cfg, tmp)
    print(f"soak: G={args.groups} P={args.peers} E={args.e} "
          f"target={args.target_commits} commits, dir={tmp}", flush=True)

    for t in range(40 * cfg.election_ticks):
        node.tick()
        if t > cfg.election_ticks and (node._hints >= 0).all():
            break
    print(f"elected all groups at tick {node.metrics.ticks}", flush=True)

    from raftsql_tpu.runtime.db import _expand_commit_item

    def drain(q):
        # _expand_commit_item understands every live queue-item shape
        # (per-group RAW_PLAIN batches AND the whole-tick RAW_MANY item
        # the fused publish emits since the one-item-per-tick change) —
        # counting raw tuples undercounted a full tick's commits as 1.
        n = 0
        while True:
            try:
                item = q.get_nowait()
            except Exception:
                return n
            if isinstance(item, tuple):
                n += len(_expand_commit_item(item))

    committed = 0
    t0 = time.perf_counter()
    rss_first = None
    tick_no = 0
    payload = b"SET k soak-value-payload"
    while committed < args.target_commits:
        for g in range(args.groups):
            node.propose_many(
                g, [payload] * args.e)
        for _ in range(args.compact_every):
            node.tick()
            tick_no += 1
            for i, q in enumerate(node._commit_qs):
                n = drain(q)      # drain every peer; count peer 0 only
                if i == 0:
                    committed += n
        node.compact(keep=args.keep)
        ents = sum(node.plogs[0].length(g) - node.plogs[0].start(g)
                   for g in range(0, args.groups,
                                  max(args.groups // 1000, 1)))
        segs = sum(len(os.listdir(d)) for d in node.dirs)
        r = rss_mb()
        # Baseline RSS at the first round whose floor has advanced:
        # before that the per-group retained window is still filling.
        if rss_first is None and node.plogs[0].start(0) > 0:
            rss_first = r
        print(f"tick={tick_no} commits={committed} rss_mb={r:.0f} "
              f"plog_entries_sampled={ents} wal_files={segs} "
              f"rate={committed / (time.perf_counter() - t0):,.0f}/s",
              flush=True)
    dt = time.perf_counter() - t0
    r_end = rss_mb()
    floor0 = node.plogs[0].start(0)
    ok = (rss_first is not None
          and r_end <= args.rss_budget_x * rss_first and floor0 > 0
          and committed >= args.target_commits)
    print(f"{'PASS' if ok else 'FAIL'}: {committed} commits in {dt:.0f}s "
          f"({committed / dt:,.0f}/s), rss {rss_first:.0f} -> "
          f"{r_end:.0f} MB (budget {args.rss_budget_x}x), "
          f"g0 floor={floor0}", flush=True)
    node.stop()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
