"""Build + run the native WAL stress harness under sanitizers.

`make native-sanitize` runs the asan and ubsan variants (the existing
`make tsan` target covers ThreadSanitizer); `--san tsan` adds it here
for a one-command full pass.  The stress harness (native/wal_stress.cc)
drives 4 threads of appends/hardstate/compact/snapshot/sync on one WAL
handle — the exact surface the serving stack hits from its apply and
HTTP threads — so a clean pass means the C++ fast path holds up where
raftlint's thread-ownership rule guards the Python side.

Exit 0: every requested sanitizer built, ran, and reported nothing.
Exit 0 with SKIP: no toolchain (hosts without g++ run the Python WAL
backend, so there is nothing to sanitize).  Exit 1: a sanitizer fired
or the stress run failed.
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raftsql_tpu.native.build import build_wal_stress  # noqa: E402


def run_one(san: str, iters: int) -> bool:
    exe = build_wal_stress(san)
    if exe is None:
        print(f"native-sanitize[{san}]: SKIP (toolchain unavailable)")
        return True
    with tempfile.TemporaryDirectory(
            prefix=f"wal-{san}-") as d:
        env = dict(os.environ)
        # halt_on_error makes asan's exit code authoritative; ubsan's
        # -fno-sanitize-recover already aborts on the first diagnostic.
        env.setdefault("ASAN_OPTIONS", "halt_on_error=1")
        proc = subprocess.run([exe, d, str(iters)], env=env,
                              capture_output=True, text=True,
                              timeout=600)
    if proc.returncode != 0:
        print(f"native-sanitize[{san}]: FAIL rc={proc.returncode}")
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        return False
    print(f"native-sanitize[{san}]: ok ({iters} iters)")
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run the WAL stress harness under sanitizers")
    ap.add_argument("--san", action="append", default=None,
                    choices=["asan", "ubsan", "tsan"],
                    help="sanitizer to run (repeatable; default "
                         "asan + ubsan)")
    ap.add_argument("--iters", type=int, default=2000,
                    help="stress iterations per thread (default 2000)")
    args = ap.parse_args(argv)
    sans = args.san or ["asan", "ubsan"]
    ok = all([run_one(s, args.iters) for s in sans])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
