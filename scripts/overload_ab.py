"""Saturation A/B: admission control on vs off at 2x offered load.

The acceptance artifact for the overload plane (PR 20).  One process
boots the fused runtime twice on identical data and drives the SAME
seeded open-loop workload at ~2x the engine's measured closed-loop
capacity:

  arm "off"  — no controller attached (the pre-PR-20 behavior): every
               offered PUT queues, the propose backlog grows without
               bound for as long as the load lasts, and tail latency
               is the whole backlog's drain time.
  arm "on"   — OverloadController attached with a bounded budget:
               offers beyond the budget are REFUSED up front
               (Overloaded -> the HTTP planes' 429), the backlog never
               exceeds the cap, and the latency of everything actually
               admitted stays bounded by cap/drain-rate.

A calibration phase measures closed-loop capacity first, so "2x load"
means 2x THIS machine's observed rate, not a magic number.  The report
lands in bench_logs/ with both arms' goodput, p50/p99 ack latency,
queue peaks, and the controller's shed/brownout attribution.

Deterministic by construction (raftlint determinism scope covers
scripts/): load shape from --seed, pacing from monotonic clocks, no
wall-clock timestamps in the report.

Usage:  python scripts/overload_ab.py [--seed 0] [--out bench_logs/...]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DRAIN_TIMEOUT_S = 60.0


def _boot(tmp, groups):
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.fused import FusedClusterNode, FusedPipe

    # tick_interval_s matches the loop pace so the step clock is
    # TRUTHFUL: deadline_steps converts wall budgets at the real step
    # cadence (an untimed cfg would convert at the 0.1 ms/step floor
    # and stretch every deadline 5x).
    cfg = RaftConfig(num_groups=groups, num_peers=3, log_window=64,
                     max_entries_per_msg=4, tick_interval_s=0.0005)
    node = FusedClusterNode(cfg, os.path.join(tmp, "data"))
    node.start(interval_s=0.0005)
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        os.path.join(tmp, f"g{g}.db")), pipe=FusedPipe(node),
        num_groups=groups)
    return node, rdb


def _prep_tables(rdb, groups):
    for g in range(groups):
        err = rdb.propose("CREATE TABLE IF NOT EXISTS kv "
                          "(k TEXT PRIMARY KEY, v TEXT)", g).wait(10.0)
        if err is not None:
            raise RuntimeError(f"create table group {g}: {err}")


def _calibrate(node, rdb, groups, n=600):
    """Open-loop capacity: n pipelined PUTs, clocked to the last ack
    -> (puts/second, device-steps/second).  Open loop matters: a
    serial closed loop pays a full commit round per put and
    underestimates the engine's drain rate by an order of magnitude.
    The step rate matters too: deadlines travel in DEVICE STEPS, and
    a loaded loop ticks much slower than its idle interval — the wire
    deadline must be denominated at the observed cadence."""
    futs = []
    s0 = node._device_steps
    t0 = time.monotonic()
    for i in range(n):
        futs.append(rdb.propose("INSERT OR REPLACE INTO kv VALUES "
                                f"('cal{i}','x')", i % groups))
    for i, f in enumerate(futs):
        err = f.wait(30.0)
        if err is not None:
            raise RuntimeError(f"calibration put {i}: {err}")
    dt = max(time.monotonic() - t0, 1e-6)
    return n / dt, max((node._device_steps - s0) / dt, 1.0)


def _queue_depth(node):
    with node._prop_lock:
        return sum(len(q) for row in node._props for q in row)


def _percentile(sorted_xs, p):
    if not sorted_xs:
        return None
    k = min(int(len(sorted_xs) * p), len(sorted_xs) - 1)
    return round(sorted_xs[k] * 1000.0, 2)      # milliseconds


def _run_arm(name, seed, groups, rate, duration_s, deadline_ms,
             caps):
    """One arm: offered load at `rate` puts/s for `duration_s`.
    `caps` is None (arm off) or (group_cap, total_cap)."""
    from raftsql_tpu.overload import DeadlineExceeded, Overloaded

    tmp = tempfile.mkdtemp(prefix=f"overload-ab-{name}-")
    node, rdb = _boot(tmp, groups)
    try:
        _prep_tables(rdb, groups)
        if caps is not None:
            from raftsql_tpu.overload import OverloadController
            node.overload = OverloadController(
                groups, group_cap=caps[0], total_cap=caps[1],
                seed=seed, tick_interval_s=0.0005)

        rng = random.Random(seed)
        lat = []                 # ack latencies (s), cb-thread appended
        errs = [0]
        offered = rejected = shed = 0
        peak_depth = 0
        round_dt = 0.01
        batch = max(1, int(rate * round_dt))
        rounds = max(1, int(duration_s / round_dt))
        t_start = time.monotonic()
        next_round = t_start
        for _ in range(rounds):
            for _ in range(batch):
                offered += 1
                g = rng.randrange(groups)
                k = rng.randrange(4096)
                dl = deadline_ms if rng.random() < 0.3 else None
                t_sub = time.monotonic()

                def _acked(err, t_sub=t_sub):
                    if err is None:
                        lat.append(time.monotonic() - t_sub)
                    elif isinstance(err, DeadlineExceeded):
                        pass     # attributed via controller counters
                    else:
                        errs[0] += 1
                try:
                    rdb.propose("INSERT OR REPLACE INTO kv VALUES "
                                f"('k{k}','v')", g,
                                deadline_ms=dl).add_done_callback(_acked)
                except Overloaded:
                    rejected += 1
                except DeadlineExceeded:
                    shed += 1
            peak_depth = max(peak_depth, _queue_depth(node))
            next_round += round_dt
            pause = next_round - time.monotonic()
            if pause > 0:
                time.sleep(pause)
        offered_s = time.monotonic() - t_start

        # Let the backlog drain (the off arm's is the whole phase).
        t_drain = time.monotonic()
        while _queue_depth(node) > 0:
            if time.monotonic() - t_drain > DRAIN_TIMEOUT_S:
                break
            time.sleep(0.01)
        time.sleep(0.2)          # let trailing ack callbacks land
        total_s = time.monotonic() - t_start

        ov = node.overload.metrics_doc() if node.overload is not None \
            else None
        acked = len(lat)
        lat.sort()
        return {
            "arm": name,
            "offered": offered,
            "acked": acked,
            "rejected_upfront": rejected,
            "shed_upfront": shed,
            "errors": errs[0],
            "goodput_puts_per_s": round(acked / max(total_s, 1e-6), 1),
            "offered_phase_s": round(offered_s, 3),
            "total_s": round(total_s, 3),
            "ack_p50_ms": _percentile(lat, 0.50),
            "ack_p99_ms": _percentile(lat, 0.99),
            "queue_depth_peak": peak_depth,
            "overload": ov,
        }
    finally:
        rdb.close()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--duration", type=float, default=4.0,
                    help="offered-load phase seconds per arm")
    ap.add_argument("--overload-factor", type=float, default=2.0,
                    help="offered rate as a multiple of measured "
                         "closed-loop capacity")
    ap.add_argument("--out", default=None,
                    help="report path (default bench_logs/"
                         "overload_ab_s<seed>.json)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    # Calibrate on a throwaway boot so neither arm starts warm.
    tmp = tempfile.mkdtemp(prefix="overload-ab-cal-")
    node, rdb = _boot(tmp, args.groups)
    try:
        _prep_tables(rdb, args.groups)
        cap_rate, step_rate = _calibrate(node, rdb, args.groups)
    finally:
        rdb.close()
        shutil.rmtree(tmp, ignore_errors=True)

    rate = cap_rate * args.overload_factor
    # Budget: ~a quarter second of drain at capacity, so refusals are
    # certain at 2x offered while the admitted backlog stays cheap.
    total_cap = max(32, int(cap_rate * 0.25))
    group_cap = max(8, total_cap // args.groups * 2)
    # Deadline budget: half the FULL queue's drain time IN WALL TERMS.
    # The wire value is milliseconds, but the engine converts it to
    # device steps at cfg.tick_interval_s — and a loaded loop ticks at
    # its own cadence, not the configured interval.  Denominate the
    # wire number so the STEP deadline corresponds to the intended
    # wall budget at the measured step rate.
    wall_deadline_s = 0.5 * total_cap / cap_rate
    deadline_ms = max(1.0, wall_deadline_s * step_rate * 0.0005
                      * 1000.0)

    print(f"overload-ab: seed={args.seed} capacity={cap_rate:.0f}/s "
          f"steps={step_rate:.0f}/s offered={rate:.0f}/s "
          f"x{args.duration:.0f}s caps=({group_cap},{total_cap}) "
          f"deadline={deadline_ms:.0f}ms-wire "
          f"(~{wall_deadline_s * 1000:.0f}ms wall)", flush=True)

    arms = {}
    for name, caps in (("off", None), ("on", (group_cap, total_cap))):
        arms[name] = _run_arm(name, args.seed, args.groups, rate,
                              args.duration, deadline_ms, caps)
        a = arms[name]
        print(f"  {name:>3}: acked={a['acked']}/{a['offered']} "
              f"rejected={a['rejected_upfront']} "
              f"p99={a['ack_p99_ms']}ms "
              f"goodput={a['goodput_puts_per_s']}/s "
              f"queue_peak={a['queue_depth_peak']}", flush=True)

    on, off = arms["on"], arms["off"]
    verdicts = {
        # The tentpole claim: the budget is a hard memory bound.
        "bounded_on": on["queue_depth_peak"] <= total_cap,
        # 2x load genuinely oversubscribes: the uncontrolled arm's
        # backlog blows past the budget the controlled arm enforces.
        "unbounded_off": off["queue_depth_peak"] > total_cap,
        # Refusals happened (the load was actually shed, not absorbed).
        "refusals_on": on["rejected_upfront"] > 0
        or (on["overload"] or {}).get("rejected", 0) > 0,
        # Goodput floor: admission refuses EXCESS load, it must not
        # collapse the throughput of what it admits.
        "goodput_floor": on["goodput_puts_per_s"]
        >= 0.5 * off["goodput_puts_per_s"],
        # Bounded tail: the admitted backlog is capped, so p99 should
        # beat the unbounded arm's drain-the-world tail.
        "p99_improved": (on["ack_p99_ms"] or 0) < (off["ack_p99_ms"]
                                                   or float("inf")),
        # Deadline attribution: the budget is half the full queue's
        # drain time, so deadline-carrying PUTs admitted behind a full
        # queue MUST shed at staging (before WAL cost) — the per-phase
        # counters prove the shed path runs, not just the refusal one.
        "deadline_sheds_on": (on["overload"] or {}).get(
            "shed_stage", 0) > 0,
    }
    report = {
        "bench": "overload_admission_ab",
        "seed": args.seed, "groups": args.groups,
        "capacity_puts_per_s": round(cap_rate, 1),
        "device_steps_per_s": round(step_rate, 1),
        "overload_factor": args.overload_factor,
        "offered_puts_per_s": round(rate, 1),
        "duration_s": args.duration,
        "group_cap": group_cap, "total_cap": total_cap,
        "deadline_ms_wire": round(deadline_ms, 1),
        "deadline_ms_wall": round(wall_deadline_s * 1000.0, 1),
        "arms": arms, "verdicts": verdicts,
    }
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_logs", f"overload_ab_s{args.seed}.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"overload-ab: report -> {out}", flush=True)
    for k, v in verdicts.items():
        print(f"  verdict {k}: {'ok' if v else 'FAIL'}", flush=True)

    hard = ("bounded_on", "unbounded_off", "refusals_on",
            "goodput_floor", "deadline_sheds_on")
    if not all(verdicts[k] for k in hard):
        print("overload-ab: FAILED hard verdicts", flush=True)
        return 1
    if not verdicts["p99_improved"]:
        print("overload-ab: WARNING: p99 did not improve", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
