"""Profile the fused durable tick's host phases (cProfile over ~N ticks).

Usage: JAX_PLATFORMS=cpu python scripts/profile_fused.py [G] [E] [TICKS]
Prints the cumulative top of the profile plus the runtime's own
phase_ms_per_tick, so the t_wal/t_publish split can be attributed to
individual callees (WAL C call vs payload log vs numpy marshalling vs
queue traffic).
"""
import cProfile
import pstats
import sys
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")

from raftsql_tpu.config import RaftConfig  # noqa: E402
from raftsql_tpu.runtime.fused import FusedClusterNode  # noqa: E402


def main() -> None:
    G = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    E = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    ticks = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    cfg = RaftConfig(num_groups=G, num_peers=3, log_window=max(64, 4 * E),
                     max_entries_per_msg=E, tick_interval_s=0.0)
    tmp = tempfile.mkdtemp(prefix="prof-fused-")
    node = FusedClusterNode(cfg, tmp)
    for t in range(40 * cfg.election_ticks):
        node.tick()
        if t > cfg.election_ticks and (node._hints >= 0).all():
            break
    print(f"elected {int((node._hints >= 0).sum())}/{G}")

    def drain(apply: bool) -> int:
        import queue as _q
        n = 0
        q = node.commit_q(0)
        while True:
            try:
                item = q.get_nowait()
            except _q.Empty:
                break
            if isinstance(item, tuple):
                from raftsql_tpu.runtime.db import iter_plain_batches
                for _g, _b, datas in iter_plain_batches(item):
                    n += len(datas)
            # drop: profiling the producer side only
        return n

    cmds = [f"SET k{i} v".encode() for i in range(ticks * E)]
    for g in range(G):
        node.propose_many(g, cmds)
    drain(False)
    m = node.metrics
    m.ticks = 0
    m.t_device_ms = m.t_wal_ms = m.t_publish_ms = 0.0

    prof = cProfile.Profile()
    prof.enable()
    for _ in range(ticks):
        node.tick()
        drain(False)
    prof.disable()
    snap = node.metrics.snapshot()["phase_ms_per_tick"]
    print("phase_ms_per_tick:", {k: round(v, 2) for k, v in snap.items()
                                 if v})
    st = pstats.Stats(prof)
    st.sort_stats("cumulative")
    st.print_stats(28)
    node.stop()


if __name__ == "__main__":
    main()
