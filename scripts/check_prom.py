"""Metrics lint: scrape a live node's Prometheus exposition and
validate it under a strict parser.

Two modes:

  * `--url http://host:port` — scrape an already-running node;
  * no arguments (CI default) — boot a `--fused` server per HTTP plane
    (aio and threaded), drive a couple of PUTs through it, then scrape.

Per scraped node it checks:

  1. `GET /metrics?format=prom` parses under `parse_prom` below — a
     deliberately strict reading of the Prometheus text exposition
     format (metric/label name charsets, TYPE-before-samples, samples
     of one metric contiguous, no duplicate series, parsable values);
  2. `Accept: application/openmetrics-text` negotiation returns the
     same exposition and the right Content-Type;
  3. ROUND TRIP: every numeric leaf of the JSON `GET /metrics`
     document appears as a sample (same shared mapping —
     raftsql_tpu/utils/metrics.py prom_samples — so a field added to
     the JSON can never silently miss the exposition);
  4. a few load-bearing series are present: the per-group top-K
     (`raftsql_group_propose_rate`), the tick-phase summary
     (`raftsql_tick_phase_ms`), the core counters, and the
     leadership-transfer outcome counters; the CI boot additionally
     enables `--placement` and requires the placement-controller
     gauges (`raftsql_placement_*`).

tests/test_obs.py imports `parse_prom` so the in-process tests and
this live-node lint enforce the same grammar.  Exit 0 = clean.
"""
from __future__ import annotations

import argparse
import http.client
import json
import os
import re
import shutil
import socket
import subprocess
import sys
import tempfile
import time
from typing import Dict, FrozenSet, List, Tuple

_METRIC_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^{}]*)\})?"
    r" (?P<value>\S+)$")
_LABEL_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")

SampleKey = Tuple[str, FrozenSet[Tuple[str, str]]]


def _family(name: str) -> str:
    """The metric family a sample line belongs to (summary/histogram
    child series share the parent's TYPE declaration)."""
    for suffix in ("_count", "_sum", "_bucket"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def parse_prom(text: str) -> Dict[SampleKey, float]:
    """Strictly parse a Prometheus text exposition; raises ValueError
    with the offending line on any format violation.  Returns
    {(name, frozenset(labels.items())): value}."""
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    samples: Dict[SampleKey, float] = {}
    typed: Dict[str, str] = {}
    current_family: str = ""
    seen_families: set = set()
    for lineno, line in enumerate(text.split("\n")[:-1], 1):
        if line == "":
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) < 4 or parts[0] != "#" \
                    or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: malformed comment "
                                 f"{line!r}")
            name = parts[2]
            if not _METRIC_RE.match(name):
                raise ValueError(f"line {lineno}: bad metric name "
                                 f"{name!r}")
            if parts[1] == "TYPE":
                if parts[3] not in _TYPES:
                    raise ValueError(f"line {lineno}: unknown type "
                                     f"{parts[3]!r}")
                if name in typed:
                    raise ValueError(f"line {lineno}: duplicate TYPE "
                                     f"for {name}")
                typed[name] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name = m.group("name")
        fam = _family(name)
        decl = fam if fam in typed else name
        if decl not in typed:
            raise ValueError(f"line {lineno}: sample {name} has no "
                             "preceding TYPE declaration")
        # Samples of one family must be contiguous.
        if decl != current_family:
            if decl in seen_families:
                raise ValueError(f"line {lineno}: samples of {decl} "
                                 "are not contiguous")
            seen_families.add(decl)
            current_family = decl
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw is not None:
            stripped = _LABEL_PAIR_RE.sub("", raw)
            if stripped.strip(", ") != "":
                raise ValueError(f"line {lineno}: malformed labels "
                                 f"{raw!r}")
            for k, v in _LABEL_PAIR_RE.findall(raw):
                if not _LABEL_RE.match(k):
                    raise ValueError(f"line {lineno}: bad label name "
                                     f"{k!r}")
                if k in labels:
                    raise ValueError(f"line {lineno}: duplicate label "
                                     f"{k!r}")
                labels[k] = v
        sval = m.group("value")
        try:
            value = float(sval)
        except ValueError:
            raise ValueError(f"line {lineno}: unparsable value "
                             f"{sval!r}") from None
        key = (name, frozenset(labels.items()))
        if key in samples:
            raise ValueError(f"line {lineno}: duplicate series "
                             f"{name}{sorted(labels.items())}")
        samples[key] = value
    return samples


def check_round_trip(json_doc: dict, samples: Dict[SampleKey, float]
                     ) -> List[str]:
    """Every numeric JSON leaf must have a sample (names + labels; the
    value may have moved between the two scrapes)."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from raftsql_tpu.utils.metrics import prom_samples
    missing = []
    for name, labels, _value in prom_samples(json_doc):
        if (name, frozenset(labels.items())) not in samples:
            missing.append(f"{name}{sorted(labels.items())}")
    return missing


# ---------------------------------------------------------------------------
# Live-node scraping.


def _get(host: str, port: int, path: str, headers=None,
         timeout: float = 10.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path, headers=headers or {})
        r = conn.getresponse()
        return r.status, dict(r.getheaders()), r.read().decode("utf-8")
    finally:
        conn.close()


def lint_url(host: str, port: int, label: str = "",
             extra_required: Tuple[str, ...] = ()) -> None:
    tag = label or f"{host}:{port}"
    status, _h, json_text = _get(host, port, "/metrics")
    assert status == 200, (tag, status)
    json_doc = json.loads(json_text)

    status, hdrs, prom_text = _get(host, port, "/metrics?format=prom")
    assert status == 200, (tag, status)
    ctype = {k.lower(): v for k, v in hdrs.items()}.get(
        "content-type", "")
    assert ctype.startswith("text/plain"), (tag, ctype)
    samples = parse_prom(prom_text)
    assert samples, f"{tag}: empty exposition"

    # Accept-header negotiation must serve the same exposition.
    status, hdrs, nego = _get(
        host, port, "/metrics",
        headers={"Accept": "application/openmetrics-text"})
    assert status == 200, (tag, status)
    parse_prom(nego)

    missing = check_round_trip(json_doc, samples)
    assert not missing, (f"{tag}: {len(missing)} JSON fields missing "
                         f"from the exposition, e.g. {missing[:5]}")

    required_series = ("raftsql_ticks", "raftsql_commits",
                       "raftsql_faults_crashes",
                       "raftsql_transfers_initiated",
                       "raftsql_transfers_completed",
                       "raftsql_transfers_aborted",
                       "raftsql_transfers_refused",
                       # PR 12 read fast path: present (0 on the
                       # engine — hits land at workers) so dashboards
                       # can rate() them unconditionally.
                       "raftsql_reads_shm_hits",
                       "raftsql_reads_shm_fallbacks",
                       "raftsql_reads_read_index_batched",
                       # Quorum geometry: effective per-phase quorum
                       # sizes + witness census/appends, present even
                       # on default-geometry clusters so dashboards
                       # can alert on a drifting config.
                       "raftsql_quorum_write_size",
                       "raftsql_quorum_election_size",
                       "raftsql_quorum_witnesses",
                       "raftsql_witness_appends",
                       # Overload plane (raftsql_tpu/overload/):
                       # admission + shed + brownout counters, present
                       # (0) even with admission disabled so
                       # dashboards can rate() them unconditionally.
                       "raftsql_overload_admitted",
                       "raftsql_overload_rejected",
                       "raftsql_overload_shed_edge",
                       "raftsql_overload_shed_stage",
                       "raftsql_overload_brownouts",
                       "raftsql_overload_queue_depth",
                       ) + extra_required
    for required in required_series:
        assert any(n == required for (n, _l) in samples), \
            f"{tag}: required series {required} absent"
    print(f"check_prom: {tag}: OK ({len(samples)} series, "
          f"{len(prom_text.splitlines())} lines)")


def lint_fused_server(engine: str) -> None:
    """Boot one --fused server on HTTP plane `engine`, drive writes
    (so per-group traffic and phase histograms are live), scrape and
    validate both exposition paths."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tmp = tempfile.mkdtemp(prefix=f"check-prom-{engine}-")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(os.path.join(tmp, "server.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
         "--port", str(port), "--groups", "2", "--tick", "0.005",
         "--http-engine", engine, "--placement",
         "--placement-interval", "0.2", "--reshard"],
        cwd=tmp, env=env, stdout=logf, stderr=logf)
    try:
        deadline = time.monotonic() + 90
        while True:
            if proc.poll() is not None or time.monotonic() > deadline:
                with open(os.path.join(tmp, "server.log")) as f:
                    raise RuntimeError(
                        f"server ({engine}) not ready; log tail: "
                        + f.read()[-800:])
            try:
                conn = http.client.HTTPConnection("127.0.0.1", port,
                                                  timeout=5)
                conn.request("PUT", "/",
                             body=b"CREATE TABLE IF NOT EXISTS "
                                  b"t (v text)")
                if conn.getresponse().status in (204, 400):
                    conn.close()
                    break
                conn.close()
            except OSError:
                pass
            time.sleep(0.3)
        def put(body: str, group: int) -> int:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=10)
            try:
                conn.request("PUT", "/", body=body.encode(),
                             headers={"X-Raft-Group": str(group)})
                r = conn.getresponse()
                r.read()
                return r.status
            finally:
                conn.close()

        for g in range(2):      # schema per raft group
            assert put("CREATE TABLE IF NOT EXISTS t (v text)",
                       g) == 204
        for i in range(8):
            assert put(f"INSERT INTO t (v) VALUES ('{i}')",
                       i % 2) == 204
        lint_url("127.0.0.1", port, label=f"fused/{engine}",
                 extra_required=(
                     "raftsql_placement_issued",
                     "raftsql_placement_refused",
                     "raftsql_placement_last_imbalance",
                     "raftsql_placement_backoff_groups",
                     # Elastic keyspace (raftsql_tpu/reshard/): verb
                     # counters, mapping epoch, and the per-verb
                     # duration histograms — present (0) from boot so
                     # dashboards can rate() them unconditionally.
                     "raftsql_reshard_splits",
                     "raftsql_reshard_merges",
                     "raftsql_reshard_migrations",
                     "raftsql_reshard_aborted",
                     "raftsql_reshard_resumed",
                     "raftsql_reshard_epoch",
                     "raftsql_reshard_active",
                     "raftsql_reshard_duration_split_count",
                     "raftsql_reshard_duration_merge_count",
                     "raftsql_reshard_duration_migrate_count",
                     # Read-replica tier (raftsql_tpu/replica/):
                     # stream-publisher counters, present (0) even
                     # with --replica-listen off so dashboards can
                     # rate() them unconditionally.
                     "raftsql_replica_subscribers",
                     "raftsql_replica_deltas_tx",
                     "raftsql_replica_bases_tx",
                     "raftsql_replica_resyncs",
                     "raftsql_replica_refusals",
                     "raftsql_replica_lag_ms"))
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except Exception:                               # noqa: BLE001
            proc.kill()
        logf.close()
        shutil.rmtree(tmp, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Prometheus exposition lint for raftsql /metrics")
    ap.add_argument("--url", action="append", default=[],
                    help="scrape this base URL (http://host:port) "
                         "instead of booting fused servers")
    args = ap.parse_args(argv)
    if args.url:
        for u in args.url:
            m = re.match(r"https?://([^:/]+):(\d+)", u)
            if not m:
                print(f"check_prom: bad url {u}", file=sys.stderr)
                return 2
            lint_url(m.group(1), int(m.group(2)))
        return 0
    # CI default: both HTTP planes, one fused boot each.
    for engine in ("aio", "threaded"):
        lint_fused_server(engine)
    return 0


if __name__ == "__main__":
    sys.exit(main())
