"""Build-check the native WAL group-commit path (`make native-check`).

Compiles native/wal.cc (via the ordinary loader), then exercises the
group-commit plumbing end to end on the NATIVE backend: per-peer views
of one shared WAL write biased records through the combined
walplog_put_uniform call and the native payload log, one fsync covers
all peers, and replay splits per peer.  Exits 0 on pass (or SKIP when
no toolchain), 1 on any mismatch — CI runs this next to `make native`
so a wal.cc change that breaks the bias ABI fails the build step, not
a downstream serving run.
"""
from __future__ import annotations

import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    from raftsql_tpu.native.build import load_native_plog, load_native_wal
    from raftsql_tpu.storage.log import NativePayloadLog
    from raftsql_tpu.storage.wal import GroupCommitWAL

    if load_native_wal() is None:
        print("native-check: SKIP (no toolchain; Python backend covers "
              "this host)")
        return 0
    plog_lib = load_native_plog()
    if plog_lib is None:
        print("native-check: FAIL: wal built but plog ABI missing",
              file=sys.stderr)
        return 1
    P, G = 3, 2
    with tempfile.TemporaryDirectory(prefix="native-gc-") as tmp:
        d = os.path.join(tmp, "gc")
        gw = GroupCommitWAL(d, num_peers=P, num_groups=G)
        if gw.base._lib is None:
            print("native-check: FAIL: shared WAL fell back to Python",
                  file=sys.stderr)
            return 1
        views = [gw.view(p) for p in range(P)]
        plogs = [NativePayloadLog(G, plog_lib) for _ in range(P)]
        for p, v in enumerate(views):
            datas = [f"p{p}e{i}".encode() for i in range(3)]
            blob = b"".join(datas)
            import numpy as np
            lens = np.fromiter(map(len, datas), np.uint32, 3)
            ok = v.append_ranges_uniform(plogs[p], [0, 1], [1, 1],
                                         [2, 1], [1, 1], blob, lens)
            if not ok:
                print("native-check: FAIL: combined call unavailable",
                      file=sys.stderr)
                return 1
            v.set_hardstates([0, 1], [1, 1], [-1, -1], [2, 1])
        for v in views:
            v.sync()
        if gw.group_commits != 1:
            print(f"native-check: FAIL: {gw.group_commits} fsyncs for "
                  "one barrier round", file=sys.stderr)
            return 1
        for v in views:
            v.close()
        flat = GroupCommitWAL.replay_flat(d)
        for p in range(P):
            mine = GroupCommitWAL.split_replay(flat, p, G)
            want0 = [f"p{p}e0".encode(), f"p{p}e1".encode()]
            if [e[1] for e in mine[0].entries] != want0 \
                    or [e[1] for e in mine[1].entries] \
                    != [f"p{p}e2".encode()]:
                print(f"native-check: FAIL: peer {p} replay mismatch: "
                      f"{mine}", file=sys.stderr)
                return 1
            if plogs[p].try_slice(0, 1, 2) != want0:
                print(f"native-check: FAIL: peer {p} plog mismatch",
                      file=sys.stderr)
                return 1
    print("native-check: ok (group-commit bias path, 1 fsync / round, "
          "per-peer replay split)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
