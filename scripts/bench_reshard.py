"""Zipfian split-hottest rebalancing demo (the elastic-keyspace bench).

Boots the fused runtime with the reshard plane, drives a seeded
zipfian keyed workload (a handful of keys carry most of the traffic,
so one group runs hot), then lets the placement controller's
`split_hottest` verb carve half of the hot group's hash slots out to
the least-loaded group.  The same workload runs again under the new
mapping and the before/after per-group traffic shares land as one
JSON report in bench_logs/ — the acceptance artifact showing the
keyspace actually rebalances under skew.

Deterministic by construction (raftlint determinism scope covers
scripts/): the load shape comes entirely from --seed, pacing from
monotonic clocks, and the report carries no wall-clock timestamps.

Usage:  python scripts/bench_reshard.py [--seed 0] [--out bench_logs/...]
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

ACK_TIMEOUT_S = 10.0


def _put(rdb, plane, key, value):
    g, sql = plane.kv_put(key, value)
    fut = rdb.propose(sql, g)
    err = fut.wait(ACK_TIMEOUT_S)
    if err is not None:
        raise RuntimeError(f"put {key!r} refused: {err}")
    return g


def _zipf_keys(rng, nkeys, count, s=1.2):
    """`count` seeded zipfian draws over `nkeys` distinct keys."""
    weights = [1.0 / (r + 1) ** s for r in range(nkeys)]
    keys = [f"user{r}" for r in range(nkeys)]
    return rng.choices(keys, weights=weights, k=count)


def _group_loads(plane, hits):
    """Per-group PUT counts under the plane's CURRENT mapping."""
    loads = {g: 0 for g in range(plane.db.num_groups)}
    for k, n in hits.items():
        loads[plane.keymap.group_of(k)] += n
    return loads


def _row_counts(plane):
    out = {}
    for g in range(plane.db.num_groups):
        try:
            rows = plane._rows(g, "SELECT count(*) FROM kv")
            out[g] = int(rows[0][0])
        except Exception:               # noqa: BLE001 - no kv table yet
            out[g] = 0
    return out


def _share(loads):
    total = sum(loads.values()) or 1
    hot = max(loads.values())
    return round(hot / total, 4)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--groups", type=int, default=4)
    ap.add_argument("--nslots", type=int, default=32)
    ap.add_argument("--keys", type=int, default=256)
    ap.add_argument("--puts", type=int, default=800,
                    help="PUTs per load phase")
    ap.add_argument("--out", default=None,
                    help="report path (default bench_logs/"
                         "reshard_zipfian_s<seed>.json)")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from raftsql_tpu.config import RaftConfig
    from raftsql_tpu.models.sqlite_sm import SQLiteStateMachine
    from raftsql_tpu.placement import PlacementController
    from raftsql_tpu.reshard.plane import ReshardPlane
    from raftsql_tpu.runtime.db import RaftDB
    from raftsql_tpu.runtime.fused import FusedClusterNode, FusedPipe

    tmp = tempfile.mkdtemp(prefix="bench-reshard-")
    cfg = RaftConfig(num_groups=args.groups, num_peers=3,
                     log_window=64, max_entries_per_msg=8,
                     tick_interval_s=0.0)
    node = FusedClusterNode(cfg, os.path.join(tmp, "data"))
    node.start(interval_s=0.0005)
    rdb = RaftDB(lambda g: SQLiteStateMachine(
        os.path.join(tmp, f"g{g}.db")), pipe=FusedPipe(node),
        num_groups=args.groups)
    plane = ReshardPlane(rdb, nslots=args.nslots,
                         ship_dir=os.path.join(tmp, "ship"))
    pc = PlacementController(node)          # not started: we drive it
    pc.reshard = plane
    rdb.placement = pc

    rng = random.Random(args.seed)
    draws = _zipf_keys(rng, args.keys, args.puts)
    hits = {}
    for k in draws:
        hits[k] = hits.get(k, 0) + 1

    print(f"bench-reshard: seed={args.seed} G={args.groups} "
          f"nslots={args.nslots} keys={args.keys} "
          f"puts={args.puts}", flush=True)

    # Phase 1: skewed load under the boot mapping.
    t0 = time.monotonic()
    for i, k in enumerate(draws):
        _put(rdb, plane, k, f"s{args.seed}v{i}")
    phase1_s = round(time.monotonic() - t0, 3)
    before = {
        "epoch": plane.keymap.epoch,
        "group_puts": _group_loads(plane, hits),
        "hot_share": _share(_group_loads(plane, hits)),
        "rows": _row_counts(plane),
    }
    print(f"  before: hot_share={before['hot_share']} "
          f"puts/group={before['group_puts']}", flush=True)

    # The controller carves half the hottest group's slots out.
    doc = pc.split_hottest()
    if doc is None:
        raise RuntimeError(f"split_hottest refused: {pc.__dict__}")
    deadline = time.monotonic() + 60.0
    while plane.coord.busy:
        plane.step()
        if time.monotonic() > deadline:
            raise RuntimeError(f"split stuck: {plane.doc()}")
        time.sleep(0.002)
    verb = {"verb": doc["verb"], "src": doc["src"], "dst": doc["dst"],
            "epoch": plane.keymap.epoch,
            "counters": dict(plane.coord.counters)}
    print(f"  split: {doc['src']} -> {doc['dst']} "
          f"epoch={plane.keymap.epoch}", flush=True)

    # Phase 2: the SAME skewed load under the new mapping.
    t0 = time.monotonic()
    for i, k in enumerate(draws):
        _put(rdb, plane, k, f"s{args.seed}w{i}")
    phase2_s = round(time.monotonic() - t0, 3)
    after = {
        "epoch": plane.keymap.epoch,
        "group_puts": _group_loads(plane, hits),
        "hot_share": _share(_group_loads(plane, hits)),
        "rows": _row_counts(plane),
    }
    print(f"  after:  hot_share={after['hot_share']} "
          f"puts/group={after['group_puts']}", flush=True)

    report = {
        "bench": "reshard_zipfian_split_hottest",
        "seed": args.seed, "groups": args.groups,
        "nslots": args.nslots, "keys": args.keys, "puts": args.puts,
        "zipf_s": 1.2,
        "before": before, "verb": verb, "after": after,
        "phase_seconds": [phase1_s, phase2_s],
        "improved": after["hot_share"] < before["hot_share"],
    }
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_logs", f"reshard_zipfian_s{args.seed}.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"bench-reshard: report -> {out} "
          f"(hot_share {before['hot_share']} -> "
          f"{after['hot_share']})", flush=True)

    rdb.close()
    if not report["improved"]:
        print("bench-reshard: WARNING: hot share did not improve",
              flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
