"""Chaos digest pin: the committed SEED=0 histories may never drift.

`make chaos SEED=0` proves a seed reproduces against ITSELF (two runs,
one process).  This lint proves it reproduces against HISTORY: it runs
the pinned seed's families once, in-process, and compares the schedule
(or plan) digest and the committed-history result digest against
bench_logs/chaos_digests.json, which is committed to the repo.

A schedule/plan digest change means the seeded generator drew
different faults — someone reordered rng draws or edited a frozen plan
dataclass (both change every historical repro recipe).  A result
digest change with a stable schedule digest is the serious one: the
same faults against the same seed produced a DIFFERENT committed
history, i.e. an engine behavior change on the default code path.
Either way the change must be deliberate: re-pin the file in the same
commit and say why in the commit message.

    python scripts/check_digests.py            # verify (CI)
    python scripts/check_digests.py --update   # re-pin after a
                                               # deliberate change

Exit 0 = every family matches the pin.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

PIN = os.path.join(_REPO, "bench_logs", "chaos_digests.json")


def _families(seed: int):
    """family name -> (report dict, schedule-digest key)."""
    from raftsql_tpu.chaos import schedule as S
    from raftsql_tpu.chaos.run import (_run_fused, _run_pod, _run_quorum,
                                       _run_replica)

    yield "default", _run_fused(S.generate(seed, ticks=240)), \
        "schedule_digest"
    yield "quorum", _run_quorum(S.generate_quorum(seed)), \
        "plan_digest"
    # The pod family's result digest is its invariant-VERDICT digest
    # (proc-plane determinism tier: the committed history crosses N
    # real kernels), so the pin proves the plan drew the same faults
    # and every invariant still passes with the same fired families.
    yield "pod", _run_pod(S.generate_pod(seed)), "plan_digest"
    # Same determinism tier for the read-replica nemesis: plan digest
    # + invariant verdicts + fired fault families.
    yield "replica", _run_replica(S.generate_replica(seed)), \
        "plan_digest"
    # Overload nemesis (raftsql_tpu/overload/): fully deterministic
    # fused-plane tier — the committed history under bounded admission
    # is bit-reproducible, so the pin covers both the seeded offered-
    # load script and the admission/shed behaviour on the hot path.
    from raftsql_tpu.chaos.run import _run_overload
    yield "overload", _run_overload(S.generate_overload(seed)), \
        "plan_digest"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the pin file from this run")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    with open(PIN, encoding="utf-8") as f:
        pinned = json.load(f)
    seed = int(pinned["seed"])

    got = {}
    ok = True
    for name, report, skey in _families(seed):
        got[name] = {skey: report[skey],
                     "result_digest": report["result_digest"]}
        want = pinned["families"].get(name)
        if args.update:
            print(f"check_digests: {name}: {got[name]}")
            continue
        if want is None:
            print(f"check_digests: FAIL {name}: no pin committed "
                  f"(got {got[name]})", file=sys.stderr)
            ok = False
        elif want != got[name]:
            print(f"check_digests: FAIL {name}: pinned {want} != "
                  f"observed {got[name]} — the SEED={seed} history "
                  f"drifted; if deliberate, re-pin with --update and "
                  f"explain in the commit", file=sys.stderr)
            ok = False
        else:
            print(f"check_digests: {name}: OK ({got[name]})")

    if args.update:
        doc = {"seed": seed, "families": got}
        tmp = tempfile.NamedTemporaryFile(
            "w", dir=os.path.dirname(PIN), suffix=".tmp",
            delete=False, encoding="utf-8")
        with tmp as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp.name, PIN)
        print(f"check_digests: pinned {PIN}")
        return 0
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
