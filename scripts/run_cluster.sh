#!/bin/sh
# Local 3-node cluster without goreman — same topology as the Procfile
# (reference Procfile:2-4).  Ctrl-C stops all nodes.
set -e
cd "$(dirname "$0")/.."
CLUSTER=http://127.0.0.1:12379,http://127.0.0.1:22379,http://127.0.0.1:32379
PIDS=""
trap 'kill $PIDS 2>/dev/null || true' INT TERM EXIT
for i in 1 2 3; do
    python -m raftsql_tpu.server.main --id $i --cluster "$CLUSTER" \
        --port ${i}2380 "$@" &
    PIDS="$PIDS $!"
done
wait
