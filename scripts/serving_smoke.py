"""Serving smoke — the CI gate for the multi-worker deployment.

Boots `server/main.py --fused --workers 2` (one engine process + two
SO_REUSEPORT HTTP workers sharing it through the propose ring), drives
it with the native epoll loadgen (`native/http_load.cc`; Python client
threads when the toolchain is absent) for a few seconds, and asserts
ZERO errors and a req/s floor.

    python scripts/serving_smoke.py
    SMOKE_SECONDS=10 SMOKE_CLIENTS=32 SMOKE_MIN_RPS=200 ...

`--reads` runs the READ-PLANE smoke instead (PR 12): the same
deployment with leases on, interleaved PUTs and session GETs from
concurrent clients, asserting (a) no session read ever answers below
the client's own PUT watermark (read-your-writes across workers), and
(b) the worker-mapped shared-memory fast path actually served reads
(`reads.shm_hits > 0` in /metrics — the zero-round-trip plane is live,
not silently falling back to the ring).

Exit 0 on pass; 1 with a diagnostic (and the server log tail) on fail.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def python_loadgen(port: int, groups: int, seconds: float,
                   clients: int) -> dict:
    from raftsql_tpu.api.client import RaftSQLClient
    client = RaftSQLClient([port], timeout_s=10,
                           max_conns_per_node=clients + 4)
    n = [0]
    errors = [0]
    stop_at = time.monotonic() + seconds

    def worker(ci: int) -> None:
        k = 0
        while time.monotonic() < stop_at:
            k += 1
            try:
                client.put(f"INSERT INTO t (v) VALUES ('c{ci}_{k}')",
                           group=(ci + k) % groups, deadline_s=10)
                n[0] += 1
            except Exception:                           # noqa: BLE001
                errors[0] += 1
    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.monotonic() - t0
    client.close()
    return {"n": n[0], "errors": errors[0], "secs": dt}


def main() -> int:
    groups = int(os.environ.get("SMOKE_GROUPS", "4"))
    seconds = float(os.environ.get("SMOKE_SECONDS", "10"))
    clients = int(os.environ.get("SMOKE_CLIENTS", "32"))
    min_rps = float(os.environ.get("SMOKE_MIN_RPS", "200"))
    workers = int(os.environ.get("SMOKE_WORKERS", "2"))
    port = free_port()
    tmp = tempfile.mkdtemp(prefix="serving-smoke-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(os.path.join(tmp, "server.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
         "--workers", str(workers), "--groups", str(groups),
         "--port", str(port), "--tick", "0.004"],
        cwd=tmp, env=env, stdout=logf, stderr=logf)

    def fail(msg: str) -> int:
        print(f"serving-smoke: FAIL: {msg}", file=sys.stderr)
        try:
            with open(os.path.join(tmp, "server.log")) as f:
                print(f.read()[-2000:], file=sys.stderr)
        except OSError:
            pass
        if proc.poll() is None:
            proc.kill()
        return 1

    try:
        from raftsql_tpu.api.client import RaftSQLClient
        boot = RaftSQLClient([port], timeout_s=10)
        boot.wait_healthy(0, deadline_s=120)
        for g in range(groups):
            boot.put("CREATE TABLE t (v text)", group=g, deadline_s=60)
        boot.close()

        loadgen = None
        if os.environ.get("SMOKE_LOADGEN", "native") == "native":
            from raftsql_tpu.native.build import build_http_load
            loadgen = build_http_load()
        if loadgen is not None:
            out = subprocess.run(
                [loadgen, str(seconds), str(clients), str(groups),
                 str(port)],
                capture_output=True, text=True, timeout=seconds + 60)
            if out.returncode != 0:
                return fail(f"loadgen rc={out.returncode}: "
                            f"{out.stderr[-500:]}")
            j = json.loads(out.stdout.strip())
        else:
            j = python_loadgen(port, groups, seconds, clients)
        rate = j["n"] / max(j["secs"], 1e-9)
        status, _, text = RaftSQLClient([port]).raw(0, "GET", "/metrics")
        m = json.loads(text) if status == 200 else {}
        print(f"serving-smoke: {j['n']} PUTs in {j['secs']:.1f}s -> "
              f"{rate:,.0f} req/s, {j['errors']} errors; "
              f"ring_workers={m.get('ring_workers')} "
              f"wal_group_commits={m.get('wal_group_commits')} "
              f"overlap_ticks={m.get('overlap_ticks')}")
        if j["errors"]:
            return fail(f"{j['errors']} errored requests")
        if rate < min_rps:
            return fail(f"{rate:,.0f} req/s below the {min_rps:,.0f} "
                        "floor")
        if m.get("ring_workers") != workers:
            return fail(f"ring_workers={m.get('ring_workers')} != "
                        f"{workers}")
        proc.send_signal(signal.SIGTERM)
        if proc.wait(timeout=30) != 0:
            return fail(f"server exit code {proc.returncode}")
        print("serving-smoke: PASS")
        return 0
    except Exception as e:                              # noqa: BLE001
        return fail(repr(e))
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:                           # noqa: BLE001
                proc.kill()
        logf.close()


def reads_main() -> int:
    """--reads: the zero-round-trip read-plane gate."""
    groups = int(os.environ.get("SMOKE_GROUPS", "2"))
    seconds = float(os.environ.get("SMOKE_SECONDS", "8"))
    clients = int(os.environ.get("SMOKE_CLIENTS", "8"))
    workers = int(os.environ.get("SMOKE_WORKERS", "2"))
    port = free_port()
    tmp = tempfile.mkdtemp(prefix="serving-smoke-reads-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    logf = open(os.path.join(tmp, "server.log"), "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "raftsql_tpu.server.main", "--fused",
         "--workers", str(workers), "--groups", str(groups),
         "--port", str(port), "--tick", "0.004",
         "--lease-ticks", "6"],
        cwd=tmp, env=env, stdout=logf, stderr=logf)

    def fail(msg: str) -> int:
        print(f"serving-smoke --reads: FAIL: {msg}", file=sys.stderr)
        try:
            with open(os.path.join(tmp, "server.log")) as f:
                print(f.read()[-2000:], file=sys.stderr)
        except OSError:
            pass
        if proc.poll() is None:
            proc.kill()
        return 1

    try:
        from raftsql_tpu.api.client import RaftSQLClient
        boot = RaftSQLClient([port], timeout_s=10)
        boot.wait_healthy(0, deadline_s=120)
        for g in range(groups):
            boot.put("CREATE TABLE t (k INTEGER PRIMARY KEY, v text)",
                     group=g, deadline_s=60)
        boot.close()

        client = RaftSQLClient([port], timeout_s=10,
                               max_conns_per_node=clients + 4)
        stats = {"puts": 0, "gets": 0, "stale": 0, "errors": 0,
                 "linear_gets": 0, "linear_stale": 0}
        mu = threading.Lock()
        stop_at = time.monotonic() + seconds

        def worker(ci: int) -> None:
            g = ci % groups
            session = 0
            k = 0
            while time.monotonic() < stop_at:
                k += 1
                try:
                    wm = client.put(
                        f"INSERT OR REPLACE INTO t VALUES "
                        f"({ci * 1000000 + k}, 'v{k}')",
                        group=g, deadline_s=10)
                    if wm:
                        session = max(session, wm)
                    # A session read carrying my own PUT watermark must
                    # never answer from below it — whichever worker,
                    # whichever path (shm fast path or ring) serves it.
                    rows, echo = client.get_session(
                        "SELECT count(*) FROM t", group=g,
                        consistency="session", session=session,
                        deadline_s=10)
                    # A linear read issued after the PUT acked must
                    # observe it, whichever path serves it — the shm
                    # lease fast path gets no refresh-window grace
                    # (this is exactly the stale-commit-column bug
                    # class: acked write invisible inside the ~2ms
                    # restamp window).
                    lrows, _ = client.get_session(
                        f"SELECT count(*) FROM t WHERE "
                        f"k = {ci * 1000000 + k}", group=g,
                        consistency="linear", deadline_s=10)
                    with mu:
                        stats["puts"] += 1
                        stats["gets"] += 1
                        stats["linear_gets"] += 1
                        if echo is not None and echo < session:
                            stats["stale"] += 1
                        if lrows.strip() != "|1|":
                            stats["linear_stale"] += 1
                except Exception:                       # noqa: BLE001
                    with mu:
                        stats["errors"] += 1
        threads = [threading.Thread(target=worker, args=(i,),
                                    daemon=True)
                   for i in range(clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        status, _, text = client.raw(0, "GET", "/metrics")
        m = json.loads(text) if status == 200 else {}
        reads = m.get("reads", {})
        client.close()
        print(f"serving-smoke --reads: {stats['puts']} PUTs / "
              f"{stats['gets']} session GETs "
              f"({stats['linear_gets']} linear), {stats['stale']} "
              f"stale, {stats['linear_stale']} linear-stale, "
              f"{stats['errors']} errors; shm_hits="
              f"{reads.get('shm_hits')} shm_fallbacks="
              f"{reads.get('shm_fallbacks')}")
        if stats["errors"]:
            return fail(f"{stats['errors']} errored requests")
        if stats["gets"] < clients:
            return fail(f"only {stats['gets']} session reads ran")
        if stats["stale"]:
            return fail(f"{stats['stale']} session reads observed a "
                        "watermark below the client's own PUT")
        if stats["linear_stale"]:
            return fail(f"{stats['linear_stale']} linear reads missed "
                        "an acked PUT (linearizability violation)")
        if not reads.get("shm_hits"):
            return fail("reads.shm_hits == 0: the shared-memory fast "
                        "path served nothing (scrape hit a worker "
                        "whose mapping is dead, or the plane is off)")
        proc.send_signal(signal.SIGTERM)
        if proc.wait(timeout=30) != 0:
            return fail(f"server exit code {proc.returncode}")
        print("serving-smoke --reads: PASS")
        return 0
    except Exception as e:                              # noqa: BLE001
        return fail(repr(e))
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:                           # noqa: BLE001
                proc.kill()
        logf.close()


if __name__ == "__main__":
    sys.exit(reads_main() if "--reads" in sys.argv[1:] else main())
