"""`go vet` stand-in — now a thin shim over raftlint.

The five original AST rules (unused imports, duplicate defs, mutable
defaults, tuple asserts, bare excepts) moved into the raftlint
framework (raftsql_tpu/analysis/) alongside the project-invariant
checkers: jit-stability, determinism (wall-clock + unseeded-random),
thread-ownership, fail-closed, memory-model.  This entry point stays
so `make vet` and muscle memory keep working; `python -m
raftsql_tpu.analysis --list` shows the rules, and per-line suppression
is `# raftlint: disable=<rule> -- why`.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from raftsql_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
