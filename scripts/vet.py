"""Static checks — the `go vet` analog (reference Makefile:17-22).

No third-party linters ship in this environment, so this is a focused
AST pass over the tree catching the defect classes that have actually
bitten or nearly bitten this codebase:

  - unused imports (symbol drift after refactors);
  - duplicate function/method definitions in one scope (silent shadowing);
  - mutable default arguments;
  - `assert (cond, msg)` tuples (always true);
  - bare `except:` clauses.

Exit 1 with findings, 0 clean.  `python scripts/vet.py [paths...]`.
"""
from __future__ import annotations

import ast
import os
import sys


def iter_py(paths):
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                for f in files:
                    if f.endswith(".py"):
                        yield os.path.join(root, f)


def check_file(path: str) -> list:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    out = []

    # ---- unused imports.
    imported: dict = {}      # name -> (lineno, qualified)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = a.asname or a.name.split(".")[0]
                imported[name] = (node.lineno, a.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                imported[name] = (node.lineno, a.name)
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
    # Names referenced in docstring-free __all__ or re-exported strings.
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if node.value in imported:
                used.add(node.value)
    if not path.endswith("__init__.py"):     # __init__ imports re-export
        for name, (lineno, qual) in sorted(imported.items()):
            if name not in used:
                out.append((path, lineno, f"unused import: {qual}"))

    # ---- duplicate defs per scope, mutable defaults, assert tuples,
    # bare excepts.
    class V(ast.NodeVisitor):
        def _defs(self, body):
            seen: dict = {}
            for st in body:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if st.name in seen and not any(
                            isinstance(d, ast.Name) and d.id in
                            ("property", "overload", "setter")
                            or isinstance(d, ast.Attribute)
                            for d in st.decorator_list):
                        out.append((path, st.lineno,
                                    f"duplicate def {st.name} "
                                    f"(first at line {seen[st.name]})"))
                    seen.setdefault(st.name, st.lineno)

        def visit_Module(self, node):
            self._defs(node.body)
            self.generic_visit(node)

        def visit_ClassDef(self, node):
            self._defs(node.body)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            for d in node.args.defaults + node.args.kw_defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                    out.append((path, node.lineno,
                                f"mutable default arg in {node.name}"))
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assert(self, node):
            if isinstance(node.test, ast.Tuple) and node.test.elts:
                out.append((path, node.lineno,
                            "assert on a tuple is always true"))
            self.generic_visit(node)

        def visit_ExceptHandler(self, node):
            if node.type is None:
                out.append((path, node.lineno, "bare except:"))
            self.generic_visit(node)

    V().visit(tree)
    return out


def main() -> int:
    paths = sys.argv[1:] or ["raftsql_tpu", "tests", "bench.py",
                             "__graft_entry__.py", "scripts"]
    findings = []
    for f in iter_py(paths):
        findings.extend(check_file(f))
    for path, lineno, msg in findings:
        print(f"{path}:{lineno}: {msg}")
    print(f"vet: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
