#!/bin/sh
# One-shot tunnel health probe: appends a status line to
# bench_logs/r5_tunnel_probes.log (timestamp + ok/wedged + latency).
cd /root/repo || exit 1
t0=$(date -u +%s)
out=$(timeout 75 python -c "
import time
t0 = time.time()
import jax
d = jax.devices()
import jax.numpy as jnp
y = float((jnp.ones((8, 8)) @ jnp.ones((8, 8))).sum())
print('ok', d[0].platform, round(time.time() - t0, 1))" 2>/dev/null | tail -1)
t1=$(date -u +%s)
if [ -z "$out" ]; then
    out="wedged timeout=$((t1 - t0))s"
fi
echo "$(date -u +%FT%TZ) $out" >> bench_logs/r5_tunnel_probes.log
